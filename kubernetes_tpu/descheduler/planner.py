"""Consolidation planning: evict-sets proven safe on the what-if overlay.

Reference shape: the descheduler project's LowNodeUtilization +
HighNodeUtilization strategies (sigs.k8s.io/descheduler) pick victims by
re-implementing scheduler predicates host-side. Here — exactly like the
autoscaler's scale-down (autoscaler/planner.py) — the feasibility proof
IS the production lattice kernel: candidate under-utilized/expensive
nodes have their rows masked out of a `whatif_overlay` copy of the live
snapshot, every resident pod's RECREATION is replayed through
`make_schedule_batch`, and a plan is accepted only when everything
re-binds with the evict-set gone (`simulate_drain_set`, the same verdict
the autoscaler trusts for single-node drains).

A plan is rejected at SIMULATION time (never discovered mid-eviction)
when:

  * pods are pending — freed capacity belongs to the backlog, and
    evicting residents to then seat lower-priority queue pods would
    invert the priority bands (the caller gates on this);
  * any resident is unmovable (no controller to recreate it, no
    safe-to-evict annotation) or sits above the victim priority ceiling
    (system bands are never consolidation victims);
  * evicting the set would drop any gang below its min-member quorum
    (coscheduling plugin's group label/annotation — the gang-strand
    rejection);
  * the kernel cannot re-place every resident strictly within the
    remaining fleet (zero newly-pending pods).

Accepted plans are strictly tighter/cheaper by construction: the node
count drops by len(evict-set) and the fleet bill drops by the set's
summed `cost_milli`.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import objects as v1
from ..api.objects import ANN_SAFE_TO_EVICT
from ..autoscaler.planner import WhatIfSimulator, simulate_drain_set
from ..scheduler.framework.plugins.coscheduling import gang_key, min_member
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.descheduler.planner")

COUNTER_PLAN_REJECTED = "descheduler_plan_rejected_total"


@dataclass
class ConsolidationPlan:
    """One accepted evict-set with everything the executor re-verifies."""

    nodes: List[str]  # evict-set, execution order
    victims: Dict[str, List[str]]  # node -> non-DaemonSet pod keys at plan time
    node_cost_milli: Dict[str, int]  # node -> cost_milli ($/h * 1000)
    replaced: int  # resident pods the simulation re-placed
    generation: int  # encoder generation the plan was proven against

    @property
    def cost_drop_milli(self) -> int:
        return sum(self.node_cost_milli.values())

    @property
    def victim_count(self) -> int:
        return sum(len(v) for v in self.victims.values())


def movable(pod: v1.Pod) -> bool:
    """Same contract as the autoscaler's scale-down: a pod may be evicted
    only if a controller will recreate it (owner references — DaemonSet
    owners included: those pods are excluded from simulation AND eviction
    separately, they die with the node) or it is annotated
    safe-to-evict."""
    if pod.metadata.owner_references:
        return True
    return (
        pod.metadata.annotations.get(ANN_SAFE_TO_EVICT, "").lower() == "true"
    )


def is_daemonset_pod(pod: v1.Pod) -> bool:
    return any(r.kind == "DaemonSet" for r in pod.metadata.owner_references)


def gang_census(node_infos) -> Dict[str, Tuple[int, int]]:
    """gang key -> (live bound members, quorum) over the whole fleet.
    Quorum is the max min-member annotation seen across members (a gang
    whose members disagree gets the conservative bound)."""
    out: Dict[str, Tuple[int, int]] = {}
    for ni in node_infos.values():
        for pod in ni.pods:
            key = gang_key(pod)
            if key is None:
                continue
            live, quorum = out.get(key, (0, 1))
            out[key] = (live + 1, max(quorum, min_member(pod)))
    return out


def gang_strands(
    evict_victims: Dict[str, List[v1.Pod]],
    census: Dict[str, Tuple[int, int]],
) -> List[str]:
    """Gang keys the evict-set would drop below quorum. Evicted members
    ARE recreated by their controllers, but between the eviction wave and
    the re-bind the gang runs below min-member — a plan that transits
    that state is rejected outright (the gang-strand rejection)."""
    planned: Dict[str, int] = {}
    for pods in evict_victims.values():
        for pod in pods:
            key = gang_key(pod)
            if key is not None:
                planned[key] = planned.get(key, 0) + 1
    return [
        key
        for key, k in planned.items()
        if census.get(key, (0, 1))[0] - k < census.get(key, (0, 1))[1]
    ]


@dataclass
class _Candidate:
    name: str
    row: int
    util: float
    cost_milli: int
    residents: List[v1.Pod] = field(default_factory=list)  # all, incl. DS
    victims: List[v1.Pod] = field(default_factory=list)  # non-DS


def plan_consolidation(
    sim: WhatIfSimulator,
    cache,
    util_threshold: float = 0.5,
    max_nodes_per_plan: int = 2,
    max_victim_priority: int = 1_000_000_000,
) -> Tuple[Optional[ConsolidationPlan], str]:
    """One planning pass. Returns (plan, "") on acceptance or
    (None, reason) — reasons land in descheduler_plan_rejected_total.

    Candidates are live, uncordoned, non-empty nodes at or under
    ``util_threshold``, ordered cheapest-to-liberate first (utilization
    asc, then cost desc — an expensive near-empty node is the best
    eviction money can buy). The evict-set grows greedily under the gang
    quorum constraint, then the WHOLE set is proven by one masked-rows
    kernel pass; an infeasible multi-node set falls back to proving its
    first node alone before giving up."""
    enc = cache.encoder
    with cache.lock:
        stats = enc.utilization_stats()
        row_names = list(enc.row_names)
        generation = enc.generation
    infos = cache.node_infos()

    candidates: List[_Candidate] = []
    for row, name in enumerate(row_names):
        if name is None or not stats.valid[row]:
            continue
        if stats.unschedulable[row] or not stats.used_any[row]:
            # cordoned nodes are someone's drain already; EMPTY nodes need
            # no eviction — deleting those is the autoscaler's scale-down
            continue
        if stats.util[row] > util_threshold:
            continue
        ni = infos.get(name)
        if ni is None or ni.node is None or ni.node.spec.unschedulable:
            continue
        cand = _Candidate(
            name=name,
            row=row,
            util=float(stats.util[row]),
            cost_milli=int(stats.cost_milli[row]),
            residents=list(ni.pods),
        )
        blocked = ""
        for pod in cand.residents:
            if is_daemonset_pod(pod):
                continue
            if not movable(pod):
                blocked = "unmovable_pods"
                break
            if (pod.priority or 0) > max_victim_priority:
                # system bands are never consolidation victims — and with
                # the pending-backlog gate this is the "never evict
                # higher-priority to seat lower" guard's second half
                blocked = "priority_band"
                break
            cand.victims.append(pod)
        if blocked:
            metrics.inc(COUNTER_PLAN_REJECTED, {"reason": blocked})
            continue
        candidates.append(cand)
    if not candidates:
        metrics.inc(COUNTER_PLAN_REJECTED, {"reason": "no_candidates"})
        return None, "no_candidates"

    # cheapest-to-liberate first: utilization asc, cost desc, stable name
    candidates.sort(key=lambda c: (c.util, -c.cost_milli, c.name))

    census = gang_census(infos)
    chosen: List[_Candidate] = []
    for cand in candidates:
        if len(chosen) >= max_nodes_per_plan:
            break
        tentative = {c.name: c.victims for c in chosen + [cand]}
        stranded = gang_strands(tentative, census)
        if stranded:
            metrics.inc(COUNTER_PLAN_REJECTED, {"reason": "gang_strand"})
            logger.info(
                "consolidation of %s rejected at simulation time: would "
                "strand gang(s) %s below min-member", cand.name, stranded,
            )
            continue
        chosen.append(cand)
    if not chosen:
        # per-candidate gang_strand increments already happened above
        return None, "gang_strand"

    attempts = [chosen] if len(chosen) == 1 else [chosen, chosen[:1]]
    for attempt in attempts:
        names = [c.name for c in attempt]
        residents = [p for c in attempt for p in c.residents]
        verdict = simulate_drain_set(sim, names, residents, kind="defrag")
        if verdict.ok:
            plan = ConsolidationPlan(
                nodes=names,
                victims={
                    c.name: [p.metadata.key for p in c.victims]
                    for c in attempt
                },
                node_cost_milli={c.name: c.cost_milli for c in attempt},
                replaced=verdict.replaced,
                generation=generation,
            )
            logger.info(
                "consolidation plan accepted: drain %s (%d pods re-place "
                "in simulation, fleet bill drops %d milli$/h)",
                names, plan.victim_count, plan.cost_drop_milli,
            )
            return plan, ""
        logger.info(
            "consolidation of %s infeasible: %s", names, verdict.reason
        )
    metrics.inc(COUNTER_PLAN_REJECTED, {"reason": "infeasible"})
    return None, "infeasible"
