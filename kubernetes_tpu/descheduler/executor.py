"""Plan execution: budgeted eviction waves with between-wave re-proof.

The executor never trusts a plan longer than one wave. Every tick
re-derives the world (leadership lease, current residents, gang census,
PDB budgets, a fresh masked-rows simulation) and compares it to what the
plan was proven against; ANY divergence discards the remainder and rolls
the cordons back — a plan is either executing against a state the kernel
just re-proved, or it is dead. Nothing is ever half-executed silently:

  * **fenced** — the leadership lease moved. A zombie descheduler writes
    NOTHING, not even the rollback uncordons (the new leader's orphan
    sweep owns those — our cordon annotation is the durable handoff).
  * **drift** — a plan node vanished, an unvetted/unmovable pod landed,
    or the re-simulation of the REMAINING evict-set stopped passing
    (e.g. a bind burst consumed the headroom the plan counted on).
    Remainder discarded, cordons rolled back, zero evictions after the
    divergence was observed.
  * **gang_change** — a fresh fleet census shows the remaining evict-set
    would now drop a gang below min-member quorum.
  * **PDB wave pause** — the pdb_blocked column is recomputed from the
    disruption controller's CURRENT budgets before every wave and any
    exhausted covering budget pauses the wave (plan stays latched; the
    store-side eviction gate stays authoritative underneath).
  * **degraded pause** — a read-only store pauses the wave mid-flight
    (counted skip); the plan stays latched and resumes when writes
    reopen, exactly the autoscaler's drain discipline.

Evictions flow through the process-wide EvictionBudget (actor
"descheduler") and the apiserver's PDB-respecting eviction subresource —
never raw pod deletes.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Set

from ..api import objects as v1
from ..api.selectors import match_labels
from ..client.apiserver import (
    LeaderFenced,
    NotFound,
    NotPrimary,
    TooManyRequests,
)
from ..runtime.consensus import DegradedWrites
from ..utils.metrics import metrics
from .planner import ConsolidationPlan, gang_census, gang_strands
from .planner import is_daemonset_pod, movable
from ..autoscaler.planner import simulate_drain_set

logger = logging.getLogger("kubernetes_tpu.descheduler.executor")

COUNTER_PLAN_ABORTS = "descheduler_plan_aborts_total"
COUNTER_ROLLBACK_UNCORDONS = "descheduler_rollback_uncordons_total"
COUNTER_EVICTIONS = "descheduler_evictions_total"
COUNTER_NODES_REMOVED = "descheduler_nodes_removed_total"
COUNTER_WAVES = "descheduler_waves_total"
COUNTER_PDB_PAUSES = "descheduler_pdb_wave_pauses_total"
COUNTER_STORE_SKIPS = "descheduler_degraded_write_skips_total"
COUNTER_COST_SAVED = "descheduler_cost_saved_milli_total"
COUNTER_PLANS_DONE = "descheduler_plans_completed_total"

# stamped with the cordon so (a) rollback only ever uncordons nodes WE
# cordoned, and (b) a crashed/fenced incarnation's cordons are durable
# state the next incarnation's orphan sweep can find and undo — the same
# adoption trick as the autoscaler's ANN_SCALE_DOWN
ANN_DEFRAG = "descheduler.kubernetes-tpu.io/defrag"


class PlanExecutor:
    """Drives one ConsolidationPlan at a time through verified waves."""

    def __init__(self, server, scheduler, sim, budget, catalog=None):
        self.server = server
        self.scheduler = scheduler
        self.sim = sim
        self.budget = budget
        self.catalog = catalog  # NodeGroupCatalog for deprovision hooks
        self.plan: Optional[ConsolidationPlan] = None
        self._cordoned: Set[str] = set()
        self._done: Set[str] = set()  # plan nodes already emptied + deleted

    @property
    def active(self) -> bool:
        return self.plan is not None

    def adopt(self, plan: ConsolidationPlan) -> None:
        assert self.plan is None, "one plan at a time"
        self.plan = plan
        self._cordoned.clear()
        self._done.clear()

    # -- orphan / rollback sweep ---------------------------------------------

    def sweep(self, nodes: List[v1.Node]) -> None:
        """Uncordon every node carrying OUR annotation that no active plan
        claims: rollback uncordons that hit a degraded store retry here,
        and cordons orphaned by a crash or fencing get undone by the next
        incarnation. Caller has already passed the leadership fence."""
        active = set(self.plan.nodes) if self.plan is not None else set()
        for node in nodes:
            name = node.metadata.name
            if name in active:
                continue
            if node.metadata.annotations.get(ANN_DEFRAG) == "true":
                self._uncordon(name)

    def _uncordon(self, name: str) -> bool:
        def mutate(n):
            if n.metadata.annotations.get(ANN_DEFRAG) != "true":
                return None  # not ours (anymore) — never undo operator cordons
            n.metadata.annotations.pop(ANN_DEFRAG, None)
            n.spec.unschedulable = False
            return n

        try:
            self.server.guaranteed_update("nodes", "", name, mutate)
        except NotFound:
            return True  # node gone: nothing left to roll back
        except (DegradedWrites, NotPrimary):
            # annotation stays on the node — the durable retry marker the
            # next sweep picks up once writes reopen
            metrics.inc(COUNTER_STORE_SKIPS, {"write": "uncordon"})
            return False
        metrics.inc(COUNTER_ROLLBACK_UNCORDONS)
        logger.info("defrag rollback: uncordoned %s", name)
        return True

    # -- one verified wave ---------------------------------------------------

    def tick(self) -> bool:
        """One wave attempt. Returns True while the plan stays latched
        (progress, pause, or nothing to do yet), False once it completed
        or aborted."""
        plan = self.plan
        if plan is None:
            return False

        # 1. leadership fence FIRST: a fenced replica writes nothing —
        # including rollback uncordons. The annotation hands the cordons
        # to the new leader's orphan sweep.
        try:
            self.scheduler.check_eviction_fence()
        except LeaderFenced:
            logger.warning(
                "defrag plan %s fenced mid-execution: leadership moved; "
                "writing nothing (new leader's sweep owns the cordons)",
                plan.nodes,
            )
            self._abort("fenced", rollback=False)
            return False

        # 2. cordon the whole evict-set before any eviction (new binds
        # must not land on nodes we are about to empty)
        for name in plan.nodes:
            if name in self._cordoned or name in self._done:
                continue
            status = self._cordon(name)
            if status == "degraded":
                return True  # plan latched; cordon retries next tick
            if status == "conflict":
                # someone else cordoned it between plan and execution —
                # an operator or the autoscaler owns this node now
                self._abort("drift")
                return False
            if status == "gone":
                self._abort("drift")
                return False
            self._cordoned.add(name)

        # 3. current residents of the remaining evict-set
        cache = self.scheduler.cache
        infos = cache.node_infos()
        remaining = [n for n in plan.nodes if n not in self._done]
        residents: List[v1.Pod] = []
        victims: List[v1.Pod] = []
        for name in remaining:
            ni = infos.get(name)
            if ni is None or ni.node is None:
                # the node vanished under the plan (operator delete,
                # lifecycle reap) — the proof is void
                self._abort("drift")
                return False
            node_victims = [p for p in ni.pods if not is_daemonset_pod(p)]
            if not node_victims:
                self._finish_node(name)
                continue
            vetted = set(plan.victims.get(name, ()))
            for p in node_victims:
                if p.metadata.key not in vetted or not movable(p):
                    # a pod the simulation never saw (direct node_name
                    # create, in-flight bind) or one nothing recreates:
                    # evicting around it is exactly the half-verified
                    # state this executor exists to forbid
                    self._abort("drift")
                    return False
            residents.extend(ni.pods)
            victims.extend(node_victims)
        remaining = [n for n in plan.nodes if n not in self._done]
        if not remaining:
            metrics.inc(COUNTER_PLANS_DONE)
            logger.info(
                "defrag plan complete: removed %s (fleet bill down %d "
                "milli$/h)", plan.nodes, plan.cost_drop_milli,
            )
            self.plan = None
            self._cordoned.clear()
            self._done.clear()
            return False
        if not victims:
            return True  # deletions in flight; cache catches up next tick

        # 4. gang quorum against the FRESH census (members may have been
        # scaled, deleted, or re-labeled since planning)
        strands = gang_strands(
            {
                name: [
                    p
                    for p in victims
                    if p.spec.node_name == name
                ]
                for name in remaining
            },
            gang_census(infos),
        )
        if strands:
            logger.warning(
                "defrag plan %s aborted: gang(s) %s would drop below "
                "min-member quorum mid-plan", plan.nodes, strands,
            )
            self._abort("gang_change")
            return False

        # 5. drift monitor: re-prove the REMAINING evict-set through the
        # production kernel before every wave — if the cluster changed in
        # a way that breaks re-placement (bind burst ate the headroom),
        # discard the remainder and roll back; zero evictions after the
        # divergence
        verdict = simulate_drain_set(
            self.sim, remaining, residents, kind="defrag"
        )
        if not verdict.ok:
            logger.warning(
                "defrag plan %s aborted on drift: re-simulation of "
                "remaining set failed (%s)", plan.nodes, verdict.reason,
            )
            self._abort("drift")
            return False

        # 6. PDB re-check before the wave: recompute the kernel's
        # pdb_blocked column from the disruption controller's CURRENT
        # budgets, and pause the wave host-side if any victim sits under
        # an exhausted budget (the store's eviction gate remains the
        # authoritative backstop underneath)
        try:
            pdbs, _ = self.server.list("poddisruptionbudgets")
        except Exception:
            logger.exception("PDB list failed; pausing wave")
            return True
        with cache.lock:
            cache.encoder.update_pdb_blocked(pdbs)
        exhausted = [
            (pdb.metadata.namespace, pdb.spec.selector)
            for pdb in pdbs
            if pdb.status.disruptions_allowed <= 0
        ]
        if exhausted and any(
            ns == p.metadata.namespace and match_labels(sel, p.metadata.labels)
            for p in victims
            for ns, sel in exhausted
        ):
            metrics.inc(COUNTER_PDB_PAUSES)
            return True  # plan stays latched; budgets refill, we resume

        # 7. the eviction wave: budgeted, through the PDB-respecting
        # eviction subresource, in plan order
        metrics.inc(COUNTER_WAVES)
        for pod in victims:
            if not self.budget.try_acquire(actor="descheduler"):
                return True  # shared bucket dry: resume next tick
            try:
                self.server.evict_pod(
                    pod.metadata.namespace, pod.metadata.name
                )
            except NotFound:
                continue  # already gone — that's the goal
            except TooManyRequests:
                # raced the disruption controller past our host-side
                # check; the store gate held — pause, don't abort
                metrics.inc(COUNTER_PDB_PAUSES)
                return True
            except (DegradedWrites, NotPrimary):
                metrics.inc(COUNTER_STORE_SKIPS, {"write": "evict"})
                return True  # pause-and-resume: plan stays latched
            metrics.inc(COUNTER_EVICTIONS)
        return True

    # -- node state transitions ----------------------------------------------

    def _cordon(self, name: str) -> str:
        """Returns ok | degraded | conflict | gone."""
        outcome = {"status": "ok"}

        def mutate(n):
            if n.metadata.annotations.get(ANN_DEFRAG) == "true":
                return None  # ours already (retry after degraded pause)
            if n.spec.unschedulable:
                outcome["status"] = "conflict"
                return None
            n.spec.unschedulable = True
            n.metadata.annotations[ANN_DEFRAG] = "true"
            return n

        try:
            self.server.guaranteed_update("nodes", "", name, mutate)
        except NotFound:
            return "gone"
        except (DegradedWrites, NotPrimary):
            metrics.inc(COUNTER_STORE_SKIPS, {"write": "cordon"})
            return "degraded"
        if outcome["status"] == "ok":
            logger.info("defrag: cordoned %s", name)
        return outcome["status"]

    def _finish_node(self, name: str) -> None:
        """The node drained clean: delete it (+ deprovision hook) and bank
        the savings. A degraded store just defers to the next tick."""
        plan = self.plan
        group = None
        if self.catalog is not None:
            ni = self.scheduler.cache.get_node_info(name)
            node = ni.node if ni is not None else None
            if node is not None:
                group = self.catalog.group_of_node(node)
        try:
            self.server.delete("nodes", "", name)
        except NotFound:
            pass
        except (DegradedWrites, NotPrimary):
            metrics.inc(COUNTER_STORE_SKIPS, {"write": "node_delete"})
            return
        self._done.add(name)
        if group is not None and group.deprovision is not None:
            try:
                group.deprovision(name)
            except Exception:
                logger.exception("deprovision hook failed for %s", name)
        metrics.inc(COUNTER_NODES_REMOVED)
        metrics.inc(
            COUNTER_COST_SAVED,
            by=float(plan.node_cost_milli.get(name, 0)),
        )
        logger.info("defrag: removed drained node %s", name)

    # -- abort ---------------------------------------------------------------

    def _abort(self, reason: str, rollback: bool = True) -> None:
        plan = self.plan
        metrics.inc(COUNTER_PLAN_ABORTS, {"reason": reason})
        if rollback and plan is not None:
            for name in plan.nodes:
                if name in self._done:
                    continue  # already deleted — nothing to uncordon
                self._uncordon(name)  # failures stay annotated for sweep()
        self.plan = None
        self._cordoned.clear()
        self._done.clear()
