"""Descheduler controller: the periodic loop around planner + executor.

Shape mirrors the ClusterAutoscaler loop (autoscaler/controller.py) —
own daemon thread, one `run_once` pass per period, every failure logged
and survived. Per pass:

  1. **Fence** — re-read the leadership lease (scheduler.check_eviction_
     fence). A fenced replica writes NOTHING this pass, not even orphan
     uncordons: those belong to the new leader's sweep.
  2. **Sweep** — uncordon nodes still carrying our defrag annotation
     that no active plan claims (rollback retries after a degraded
     store, and cordons orphaned by a crash or leadership change).
  3. **Observe** — publish the fleet fragmentation score (the
     scheduler's gauge, re-exported under the descheduler family so one
     SIGUSR2 dump shows signal next to actuation).
  4. **Act** — if a plan is latched, run one executor tick. Otherwise,
     plan: but ONLY when the unschedulable backlog is empty (freed
     capacity belongs to pending pods; consolidating while pods queue
     would evict bound work to seat queued work — the priority-band
     inversion the ISSUE forbids) and fragmentation clears the floor.

The descheduler follows scheduler leadership: cmd/scheduler.py starts it
in on_started and stops it in on_stopped, and every pass re-checks the
lease anyway (belt and suspenders — the stop() call from a lost lease
races the in-flight pass).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from ..utils.metrics import metrics
from .executor import PlanExecutor
from .planner import COUNTER_PLAN_REJECTED, plan_consolidation

logger = logging.getLogger("kubernetes_tpu.descheduler")

GAUGE_FRAGMENTATION = "descheduler_fragmentation_score"
GAUGE_ACTIVE_PLAN_NODES = "descheduler_active_plan_nodes"
COUNTER_PLANS = "descheduler_plans_total"
COUNTER_FENCED_PASSES = "descheduler_fenced_passes_total"


class Descheduler:
    def __init__(
        self,
        server,
        scheduler,
        eviction_budget,
        catalog=None,
        period_s: float = 1.0,
        util_threshold: float = 0.5,
        fragmentation_floor: float = 0.0,
        max_nodes_per_plan: int = 2,
        max_victim_priority: int = 1_000_000_000,
        cost_aware: bool = True,
    ):
        from ..autoscaler.planner import WhatIfSimulator
        from ..client.apiserver import LeaderFenced

        self._LeaderFenced = LeaderFenced
        self.server = server
        self.scheduler = scheduler
        self.period = period_s
        self.util_threshold = util_threshold
        # plans are only attempted when fragmentation exceeds this floor:
        # 0.0 means "any stranded capacity is worth a what-if pass"
        self.fragmentation_floor = fragmentation_floor
        self.max_nodes_per_plan = max_nodes_per_plan
        self.max_victim_priority = max_victim_priority
        self.sim = WhatIfSimulator(
            scheduler.cache,
            hard_pod_affinity_weight=scheduler.cfg.hard_pod_affinity_weight,
            cost_aware=cost_aware,
        )
        self.executor = PlanExecutor(
            server, scheduler, self.sim, eviction_budget, catalog=catalog
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()  # restartable across leadership cycles
        self._thread = threading.Thread(
            target=self._run, name="descheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:
                logger.exception("descheduler pass failed")
            self._stop.wait(self.period)

    # -- one pass ------------------------------------------------------------

    def run_once(self) -> None:
        # fence before ANY write this pass — sweep() uncordons are writes
        try:
            self.scheduler.check_eviction_fence()
        except self._LeaderFenced:
            metrics.inc(COUNTER_FENCED_PASSES)
            if self.executor.active:
                self.executor.tick()  # tick re-checks and aborts fenced
            return
        try:
            nodes, _ = self.server.list("nodes")
        except Exception:
            logger.exception("node list failed; skipping descheduler pass")
            return
        self.executor.sweep(nodes)

        frag = self.scheduler.fragmentation_score()
        metrics.set_gauge(GAUGE_FRAGMENTATION, frag)

        if self.executor.active:
            self.executor.tick()
        else:
            self._maybe_plan(frag)
        plan = self.executor.plan
        metrics.set_gauge(
            GAUGE_ACTIVE_PLAN_NODES,
            float(len(plan.nodes)) if plan is not None else 0.0,
        )

    def _maybe_plan(self, frag: float) -> None:
        backlog = [
            pi
            for pi in self.scheduler.queue.unschedulable_pod_infos()
            if pi.pod.metadata.deletion_timestamp is None
        ]
        if backlog:
            # pending pods own the free capacity: consolidating now would
            # evict bound (possibly higher-priority) work to make room
            # for queued work — defer until the backlog drains
            metrics.inc(COUNTER_PLAN_REJECTED, {"reason": "pending_backlog"})
            return
        if frag <= self.fragmentation_floor:
            return
        plan, reason = plan_consolidation(
            self.sim,
            self.scheduler.cache,
            util_threshold=self.util_threshold,
            max_nodes_per_plan=self.max_nodes_per_plan,
            max_victim_priority=self.max_victim_priority,
        )
        if plan is None:
            logger.debug("no consolidation plan: %s", reason)
            return
        metrics.inc(COUNTER_PLANS)
        self.executor.adopt(plan)
        self.executor.tick()  # first wave in the same pass


def descheduler_health_lines() -> List[str]:
    """Descheduler + shared eviction-budget series rendered for the
    SIGUSR2 debugger dump (scheduler/cache/debugger.py): a stuck plan, a
    paused wave, or a starved budget is diagnosable from one signal.
    Empty when no descheduler has published state in this process."""
    lines: List[str] = []
    for series in (
        metrics.snapshot_gauges("descheduler_"),
        metrics.snapshot_counters("descheduler_"),
    ):
        for name, labels, value in series:
            lines.append(metrics.format_series_line(name, labels, value))
    return lines
