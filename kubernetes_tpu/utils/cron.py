"""Minimal 5-field cron schedule parser (minute hour dom month dow).

Supports: ``*``, numbers, lists (``a,b``), ranges (``a-b``), and steps
(``*/n``, ``a-b/n``). Semantics match the reference's robfig/cron usage in
pkg/controller/cronjob: dom and dow are OR'd when both are restricted.
"""

from __future__ import annotations

import calendar
import time
from typing import List, Set, Tuple

_BOUNDS = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


def _parse_field(spec: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step < 1:
                raise ValueError(f"bad step {step_s!r}")
        if part in ("*", ""):
            a, b = lo, hi
        elif "-" in part:
            a_s, b_s = part.split("-", 1)
            a, b = int(a_s), int(b_s)
        else:
            a = b = int(part)
        if a < lo or b > hi or a > b:
            raise ValueError(f"field {spec!r} out of range [{lo},{hi}]")
        out.update(range(a, b + 1, step))
    return out


class CronSchedule:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec needs 5 fields: {spec!r}")
        self.spec = spec
        (self.minutes, self.hours, self.dom, self.months, self.dow) = (
            _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _BOUNDS)
        )
        # dom/dow OR rule applies only when both are restricted
        self._dom_star = fields[2] == "*"
        self._dow_star = fields[4] == "*"

    def _day_matches(self, tm: time.struct_time) -> bool:
        dom_ok = tm.tm_mday in self.dom
        dow_ok = (tm.tm_wday + 1) % 7 in self.dow  # cron: 0=Sunday
        if self._dom_star and self._dow_star:
            return True
        if self._dom_star:
            return dow_ok
        if self._dow_star:
            return dom_ok
        return dom_ok or dow_ok

    def next_after(self, ts: float, limit_days: int = 500) -> float:
        """Earliest scheduled time strictly after `ts` (unix seconds).

        Jumps by field instead of stepping minute-by-minute: non-matching
        months/days skip whole days, non-matching hours skip whole hours —
        bounded by ~limit_days day-steps even for never-matching specs
        ("0 0 31 2 *"), not 720k minute-steps."""
        t = int(ts // 60 + 1) * 60  # next whole minute
        deadline = ts + limit_days * 86400
        while t <= deadline:
            tm = time.localtime(t)
            if tm.tm_mon not in self.months or not self._day_matches(tm):
                # jump to next local midnight
                t = int(
                    time.mktime(
                        (tm.tm_year, tm.tm_mon, tm.tm_mday + 1, 0, 0, 0, 0, 0, -1)
                    )
                )
                continue
            if tm.tm_hour not in self.hours:
                # next LOCAL hour boundary — unix-hour arithmetic breaks in
                # zones with non-whole-hour offsets (e.g. +5:30)
                t = int(
                    time.mktime(
                        (tm.tm_year, tm.tm_mon, tm.tm_mday, tm.tm_hour + 1,
                         0, 0, 0, 0, -1)
                    )
                )
                continue
            if tm.tm_min in self.minutes:
                return float(t)
            # next matching minute within this hour, else next local hour
            later = [m for m in self.minutes if m > tm.tm_min]
            if later:
                t += (min(later) - tm.tm_min) * 60
            else:
                t = int(
                    time.mktime(
                        (tm.tm_year, tm.tm_mon, tm.tm_mday, tm.tm_hour + 1,
                         0, 0, 0, 0, -1)
                    )
                )
        raise ValueError(f"no run time within {limit_days} days for {self.spec!r}")
