"""Local HTTP debug listener: /metrics + /debug/traces for every process.

Until now only the apiserver process exposed metrics over HTTP; the
scheduler and controller-manager were SIGUSR2-only — useless the moment
you want a Prometheus scrape or a trace lookup against a live replica
without log access. This module is the small shared listener every
process family can start with ``--debug-port`` (default off):

  * ``GET /metrics``       — Prometheus exposition text (the process's
    registry, exemplar comment lines included);
  * ``GET /debug/traces``  — the tracing ring (utils/tracing.py):
    ``?id=<trace_id>`` returns one trace with its store-side stamps,
    otherwise the slowest-N completed traces (``?n=``, ``?kind=``);
  * ``GET /healthz``       — liveness.

The apiserver's REST mux serves the same two payloads from its own
port (apiserver/rest.py delegates to :func:`traces_payload`), so every
process in the control plane answers the same debug URLs.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .metrics import metrics
from .tracing import tracer


def metrics_payload() -> Tuple[bytes, str]:
    """(body, content-type) for a /metrics scrape of this process — the
    ONE place that knows batch-published tracing series need a flush
    before rendering. Shared by this listener, the apiserver REST mux,
    and the scheduler healthz handler so the three scrapes cannot
    drift."""
    tracer.publish_gauges()
    return (
        metrics.render_prometheus().encode(),
        "text/plain; version=0.0.4",
    )


def traces_payload(query: dict) -> Tuple[int, dict]:
    """The /debug/traces response body for a parsed query dict. Shared
    by this listener and the apiserver REST route so the two views
    cannot drift."""
    trace_id = query.get("id", "")
    if trace_id:
        found = tracer.get(trace_id)
        if found is None:
            return 404, {"error": f"no trace {trace_id!r} in this process"}
        return 200, found
    try:
        n = int(query.get("n", "10"))
    except ValueError:
        n = 10
    kind = query.get("kind", "pod")
    return 200, {
        "kind": kind,
        "slowest": tracer.slowest(n, kind=kind),
        "stages": tracer.stage_stats(kind=kind) if kind == "pod" else {},
    }


class _DebugHandler(BaseHTTPRequestHandler):
    server_version = "ktpu-debug"

    def log_message(self, *args):
        pass

    def _respond(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        u = urlparse(self.path)
        if u.path in ("/healthz", "/livez"):
            return self._respond(200, b"ok", "text/plain")
        if u.path == "/metrics":
            body, ctype = metrics_payload()
            return self._respond(200, body, ctype)
        if u.path == "/debug/traces":
            q = {k: v[-1] for k, v in parse_qs(u.query).items()}
            code, payload = traces_payload(q)
            return self._respond(
                code, json.dumps(payload, indent=1).encode(),
                "application/json",
            )
        return self._respond(404, b"not found", "text/plain")


def serve_debug(
    port: int, host: str = "127.0.0.1"
) -> Optional[ThreadingHTTPServer]:
    """Start the listener (daemon thread); port 0 binds an ephemeral
    port (``srv.server_address[1]``), None/negative disables. Loopback
    by default: this is an operator surface, not a service."""
    if port is None or port < 0:
        return None
    srv = ThreadingHTTPServer((host, port), _DebugHandler)
    srv.daemon_threads = True
    threading.Thread(
        target=srv.serve_forever, daemon=True, name="debug-listener"
    ).start()
    return srv
