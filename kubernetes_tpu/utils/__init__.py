"""Shared infra: metrics registry, step tracing, feature gates (component-base-lite)."""

from .metrics import Metrics, metrics  # noqa: F401
from .trace import Trace  # noqa: F401
from .featuregate import FeatureGate, default_feature_gate  # noqa: F401
