"""Metrics registry: counters, gauges, histograms with label support.

component-base/metrics-lite (reference wraps prometheus; scheduler series at
pkg/scheduler/metrics/metrics.go:51-231). Same series names are used by the
scheduler so dashboards translate: schedule_attempts_total,
e2e_scheduling_duration_seconds, scheduling_algorithm_duration_seconds,
binding_duration_seconds, pending_pods, queue_incoming_pods_total, etc.
"""

from __future__ import annotations

import bisect
import random
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

_DEF_BUCKETS = [
    0.0001, 0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05,
    0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0,
]


class Histogram:
    # exemplar slots: the largest-valued observations that carried a
    # trace id — enough to resolve "show me the p99 pod" without storing
    # an id per sample
    _MAX_EXEMPLARS = 8

    def __init__(
        self,
        buckets: Optional[List[float]] = None,
        max_samples: int = 100000,
        seed: int = 0x5EED,
    ):
        self.buckets = buckets or _DEF_BUCKETS
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        # true bounded reservoir (Algorithm R, deterministic seed): every
        # observation — first or ten-millionth — has equal probability of
        # being in the sample, so a long-run p99 tracks the live
        # distribution instead of freezing at the warmup one. Each slot
        # remembers the OBSERVATION INDEX it came from so quantiles_since
        # can still window out warmup samples.
        self._samples: List[float] = []
        self._sample_obs: List[int] = []
        self._max_samples = max_samples
        self._rng = random.Random(seed)
        # (value, exemplar) pairs, tail-biased (see observe)
        self._exemplars: List[Tuple[float, str]] = []

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.buckets, v)
        self.counts[i] += 1
        self.total += v
        self.n += 1
        if len(self._samples) < self._max_samples:
            self._samples.append(v)
            self._sample_obs.append(self.n - 1)
        else:
            j = self._rng.randrange(self.n)
            if j < self._max_samples:
                self._samples[j] = v
                self._sample_obs[j] = self.n - 1
        if exemplar:
            ex = self._exemplars
            if len(ex) < self._MAX_EXEMPLARS:
                ex.append((v, exemplar))
            else:
                mi = min(range(len(ex)), key=lambda k: ex[k][0])
                if v > ex[mi][0]:
                    ex[mi] = (v, exemplar)

    def quantile(self, q: float) -> float:
        return self.quantiles([q])[0]

    def quantiles(self, qs) -> List[float]:
        """Several quantiles from ONE sort of the reservoir."""
        if not self._samples:
            return [0.0] * len(qs)
        s = sorted(self._samples)
        return [s[min(int(q * len(s)), len(s) - 1)] for q in qs]

    def quantiles_since(self, n0: int, qs) -> List[float]:
        """Quantiles over samples whose observation index is >= n0 — lets
        a measurement window exclude warmup/compile-laden samples the
        same way callers baseline `total`/`n` (bench stage breakdown).
        Algorithm R keeps every slot's inclusion probability identical,
        so the surviving suffix samples are an unbiased window sample."""
        s = sorted(
            v for v, oi in zip(self._samples, self._sample_obs) if oi >= n0
        )
        if not s:
            return [0.0] * len(qs)
        return [s[min(int(q * len(s)), len(s) - 1)] for q in qs]

    def exemplars(self) -> List[Tuple[float, str]]:
        """(value, trace_id) pairs, largest value first."""
        return sorted(self._exemplars, reverse=True)

    def exemplar_near(self, q: float) -> Optional[Tuple[float, str]]:
        """The exemplar closest ABOVE the q-quantile (falling back to the
        largest below it): "what is the p99" becomes "show me the p99
        pod's waterfall" through the returned trace id."""
        ex = self.exemplars()
        if not ex:
            return None
        target = self.quantile(q)
        at_or_above = [e for e in ex if e[0] >= target]
        return at_or_above[-1] if at_or_above else ex[0]

    @property
    def avg(self) -> float:
        return self.total / self.n if self.n else 0.0


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = defaultdict(float)
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._hists: Dict[Tuple[str, Tuple], Histogram] = {}

    @staticmethod
    def _k(name: str, labels: Optional[dict]) -> Tuple[str, Tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def inc(self, name: str, labels: Optional[dict] = None, by: float = 1.0) -> None:
        with self._lock:
            self._counters[self._k(name, labels)] += by

    def set_gauge(self, name: str, value: float, labels: Optional[dict] = None) -> None:
        with self._lock:
            self._gauges[self._k(name, labels)] = value

    def remove_gauge(self, name: str, labels: Optional[dict] = None) -> None:
        """Retire one labeled gauge series (e.g. a departed follower's lag
        — a stale series would read as a live replica in the debugger)."""
        with self._lock:
            self._gauges.pop(self._k(name, labels), None)

    def observe(
        self,
        name: str,
        value: float,
        labels: Optional[dict] = None,
        exemplar: Optional[str] = None,
    ) -> None:
        """exemplar: a trace id to ride along with this observation —
        tail observations keep theirs, so the histogram's p99 resolves
        to an inspectable per-pod trace (utils/tracing.py)."""
        with self._lock:
            k = self._k(name, labels)
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = Histogram()
            h.observe(value, exemplar=exemplar)

    def counter(self, name: str, labels: Optional[dict] = None) -> float:
        with self._lock:
            return self._counters.get(self._k(name, labels), 0.0)

    def gauge(self, name: str, labels: Optional[dict] = None) -> Optional[float]:
        """Read back a gauge (None when never set) — the consensus/
        replication health gauges are read-path state for the SIGUSR2
        debugger dump and tests, not just exposition output."""
        with self._lock:
            return self._gauges.get(self._k(name, labels))

    def _snapshot_series(
        self, series: dict, prefix: str
    ) -> List[Tuple[str, dict, float]]:
        """(name, labels, value) for every series under prefix, sorted by
        the (name, labels) KEY tuple — sorting the dict-carrying rows
        directly raises once two series share a name (dicts don't
        order). Caller must hold self._lock."""
        return [
            (name, dict(labels), v)
            for (name, labels), v in sorted(
                series.items(), key=lambda kv: kv[0]
            )
            if name.startswith(prefix)
        ]

    def snapshot_gauges(self, prefix: str = "") -> List[Tuple[str, dict, float]]:
        """Every gauge under prefix — the debugger's replication section
        renders exactly this."""
        with self._lock:
            return self._snapshot_series(self._gauges, prefix)

    @staticmethod
    def format_series_line(name: str, labels: dict, value: float,
                           annotation: str = "") -> str:
        """One debug-dump line for a (name, labels, value) series — the
        shared renderer behind every SIGUSR2 health-lines section (the
        consensus, ride-through, data-plane, autoscaler, and read-path
        dumps all print this exact shape)."""
        label_s = (
            "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"
            if labels
            else ""
        )
        suffix = f" [{annotation}]" if annotation else ""
        return f"  {name}{label_s}: {value:g}{suffix}"

    def snapshot_counters(self, prefix: str = "") -> List[Tuple[str, dict, float]]:
        """Every counter under prefix — the debugger's data-plane
        self-defense section renders drift and guard-trip counters this
        way (counters, unlike gauges, have no enumerable label sets a
        caller could probe one by one)."""
        with self._lock:
            return self._snapshot_series(self._counters, prefix)

    def histogram(self, name: str, labels: Optional[dict] = None) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(self._k(name, labels))

    def reset(self) -> None:
        """DELETE /metrics debug endpoint behavior (server.go:237-247)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    def render_prometheus(self) -> str:
        """Prometheus exposition text format (the wire form the reference's
        legacyregistry serves on /metrics): counters and gauges as-is,
        histograms as _count/_sum plus p50/p90/p99 quantile gauges (this
        registry keeps a sample reservoir, not fixed buckets)."""

        def esc(v) -> str:
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        def fmt_labels(labels) -> str:
            if not labels:
                return ""
            inner = ",".join(
                f'{k}="{esc(v)}"' for k, v in sorted(dict(labels).items())
            )
            return "{" + inner + "}"

        lines = []
        # the whole render holds the lock (like dump()): histograms are
        # shared mutable objects, and a concurrent observe() between the
        # quantile/_sum/_count reads would emit a torn summary
        with self._lock:
            seen_types = set()
            for (name, labels), v in sorted(self._counters.items()):
                if name not in seen_types:
                    lines.append(f"# TYPE {name} counter")
                    seen_types.add(name)
                lines.append(f"{name}{fmt_labels(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                if name not in seen_types:
                    lines.append(f"# TYPE {name} gauge")
                    seen_types.add(name)
                lines.append(f"{name}{fmt_labels(labels)} {v}")
            for (name, labels), h in sorted(self._hists.items()):
                if name not in seen_types:
                    lines.append(f"# TYPE {name} summary")
                    seen_types.add(name)
                base = dict(labels) if labels else {}
                vals = h.quantiles((0.5, 0.9, 0.99))  # one sort
                for q, val in zip((0.5, 0.9, 0.99), vals):
                    ql = dict(base)
                    ql["quantile"] = f"{q:g}"
                    lines.append(f"{name}{fmt_labels(ql)} {val}")
                lines.append(f"{name}_sum{fmt_labels(labels)} {h.total}")
                lines.append(f"{name}_count{fmt_labels(labels)} {h.n}")
                for val, tid in h.exemplars():
                    # OpenMetrics-style exemplar, emitted as a comment so
                    # plain text-format 0.0.4 scrapers stay unbroken
                    lines.append(
                        f"# exemplar {name}{fmt_labels(labels)} {val} "
                        f'trace_id="{esc(tid)}"'
                    )
        return "\n".join(lines) + "\n"

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for (name, labels), v in self._counters.items():
                out[f"{name}{dict(labels)}"] = v
            for (name, labels), v in self._gauges.items():
                out[f"{name}{dict(labels)}"] = v
            for (name, labels), h in self._hists.items():
                p50, p90, p99 = h.quantiles((0.50, 0.90, 0.99))
                entry = {
                    "count": h.n,
                    "avg": h.avg,
                    "p50": p50,
                    "p90": p90,
                    "p99": p99,
                }
                ex = h.exemplar_near(0.99)
                if ex is not None:
                    entry["p99_exemplar"] = ex[1]
                out[f"{name}{dict(labels)}"] = entry
            return out


metrics = Metrics()  # process-global registry (legacyregistry equivalent)
