"""Feature gates: named alpha/beta/GA switches.

component-base/featuregate/feature_gate.go:87,294 equivalent, parsing the
same --feature-gates=Name=true map form. Gates relevant to the TPU build are
pre-registered; unknown gates error like the reference.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Mapping

ALPHA, BETA, GA = "ALPHA", "BETA", "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    pre_release: str = BETA
    locked: bool = False


DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    # TPU-native data plane per extension point (SURVEY §2.3: profile gate)
    "TPUBatchScore": FeatureSpec(default=True, pre_release=BETA),
    "TPUShardedNodes": FeatureSpec(default=True, pre_release=ALPHA),
    "DeviceOracleVerify": FeatureSpec(default=False, pre_release=ALPHA),
    # reference-parity gates the scheduler consults
    "EvenPodsSpread": FeatureSpec(default=True, pre_release=BETA),
    "PodPriority": FeatureSpec(default=True, pre_release=GA, locked=True),
    "TaintNodesByCondition": FeatureSpec(default=True, pre_release=GA),
    "PodOverhead": FeatureSpec(default=True, pre_release=BETA),
    "NonPreemptingPriority": FeatureSpec(default=False, pre_release=ALPHA),
}


class FeatureGate:
    def __init__(self, features: Mapping[str, FeatureSpec] = None):
        self._lock = threading.Lock()
        self._known = dict(features or DEFAULT_FEATURES)
        self._enabled: Dict[str, bool] = {}

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._enabled:
                return self._enabled[name]
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name}")
            return spec.default

    def set_from_map(self, m: Mapping[str, bool]) -> None:
        with self._lock:
            for name, val in m.items():
                spec = self._known.get(name)
                if spec is None:
                    raise KeyError(f"unknown feature gate {name}")
                if spec.locked and val != spec.default:
                    raise ValueError(f"cannot set locked feature gate {name}")
                self._enabled[name] = bool(val)

    def set_from_string(self, s: str) -> None:
        m = {}
        for part in filter(None, (p.strip() for p in s.split(","))):
            k, _, v = part.partition("=")
            m[k] = v.lower() in ("true", "1", "t")
        self.set_from_map(m)

    def add(self, name: str, spec: FeatureSpec) -> None:
        with self._lock:
            self._known[name] = spec


def default_feature_gate() -> FeatureGate:
    return FeatureGate()
