"""Persistent JAX compilation cache, on by default for process entries.

Every process bring-up used to pay a multi-second XLA compile storm (wave
kernel variants, scatter/gather programs, the serial batch kernel) — and
the persistent cache (`JAX_COMPILATION_CACHE_DIR`) that would amortize it
across processes was deliberately OFF: a donating scatter deserialized
from the cache was observed corrupting rows it was never asked to touch
when its donation aliased buffers a concurrent reader observed (the PR-4
`_scatter_rows_safe` incident). The generational snapshot removed that
aliasing structurally — donation only ever consumes lease-private,
unpinned buffers — so the cache is safe to enable everywhere, and the
scheduler/apiserver entry points (cmd/) plus the Makefile chaos targets
do so by default.

Opt out with ``KTPU_NO_COMPILATION_CACHE=1`` (e.g. to bisect a suspected
stale-cache artifact); point ``JAX_COMPILATION_CACHE_DIR`` somewhere
explicit to share one cache across process families (the chaos Makefile
targets use ``.jax_cache`` in the repo root).
"""

from __future__ import annotations

import logging
import os
import tempfile
from typing import Optional

logger = logging.getLogger("kubernetes_tpu.utils.compilation_cache")

DISABLE_ENV = "KTPU_NO_COMPILATION_CACHE"
DIR_ENV = "JAX_COMPILATION_CACHE_DIR"


def enable_persistent_compilation_cache(
    default_dir: Optional[str] = None,
) -> Optional[str]:
    """Point JAX at a persistent compilation cache directory and return
    it (None when disabled or JAX refuses). Call before the first jit
    dispatch; idempotent. Respects an explicit ``JAX_COMPILATION_CACHE_DIR``
    and the ``KTPU_NO_COMPILATION_CACHE`` kill switch."""
    if os.environ.get(DISABLE_ENV, "").lower() in ("1", "true", "yes"):
        return None
    cache_dir = (
        os.environ.get(DIR_ENV)
        or default_dir
        or os.path.join(tempfile.gettempdir(), "kubernetes_tpu_jax_cache")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        logger.warning("compilation cache dir %s not writable", cache_dir)
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:
        logger.exception("enabling the persistent compilation cache failed")
        return None
    # best-effort knobs (names vary across jax versions): cache even quick
    # compiles — the wave path's scatter/gather programs are individually
    # fast to compile but numerous, and cold-start pays all of them
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # pragma: no cover - knob absent in this jax
            pass
    os.environ.setdefault(DIR_ENV, cache_dir)
    logger.info("persistent JAX compilation cache: %s", cache_dir)
    return cache_dir
