"""Per-pod scheduling traces: spans from queue admit to store ack.

The metrics registry answers "what is the p99"; nothing in the system
could answer "WHERE did the p99 pod spend its time". This module is the
tail-latency attribution layer:

  * a **trace** is minted per pod at queue admission (and per wave at
    kernel launch) and accumulates **spans** — named `[t0, t1)`
    monotonic intervals (`queue`, `encode`, `device`, `readback`,
    `guard`, `assume`, `bind`, `outage.wait`, ...) — plus point
    **events** (`bind.parked`, `unschedulable`, `bind.fenced`, ...);
  * **wave traces** fan-in the N pod traces sharing one kernel launch:
    each pod span chain carries its wave's trace id, so one slow wave
    explains N slow pods;
  * completed traces land in a bounded per-process **ring buffer**
    served by the SIGUSR2 "traces" dump section, the `/debug/traces`
    REST view (slowest-N, by-id lookup), and the `--debug-port`
    listener on scheduler/controller-manager processes;
  * trace context **propagates across process boundaries**: the REST
    client attaches an ``X-Trace-Context`` header to every `/binding`
    POST, the route re-establishes the context thread-locally, and the
    store stamps the apply — or the LeaderFenced rejection — under the
    same id into a bounded store-side ledger (`stamp_bind`), so a
    zombie's fenced bind is visible as a trace event in the store
    process.

Span API contract (machine-enforced by graftlint's tracing pass): a
span is either recorded atomically with measured endpoints
(`add_span`/`add_spans`/`add_span_many` — nothing is left open) or
opened through the ``span()`` context manager, which MUST be used as a
``with`` statement so every started span is finished on all exits.

Clock discipline: every timestamp in a span is `time.monotonic()` —
never wall clock (deflake guard: NTP steps and clock skew must not
produce negative or inflated stages). Wall time appears only as trace
attributes (`since_created_s`) for cross-referencing API objects.

Concurrency: one named lock (``tracing.ring``) guards the active table,
the ring, and the store ledger; the lock is a leaf (nothing else is
acquired under it) and the shared attributes are Eraser-tracked
(`track_attrs`) so the chaos suites' lockset sanitizer machine-checks
the guard from day one. Disabled (``KTPU_TRACING=0`` or
``set_enabled(False)``) every entry point is one attribute test.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..testing.lockgraph import named_lock, track_attrs

# the cross-process propagation header (attached by RESTClient
# bind_pod/bind_pods, validated/consumed by the /binding route)
TRACE_HEADER = "X-Trace-Context"

COUNTER_STARTED = "tracing_traces_total"
COUNTER_COMPLETED = "tracing_traces_completed_total"
COUNTER_DROPPED = "tracing_traces_dropped_total"
COUNTER_STORE_STAMPS = "tracing_store_stamps_total"
GAUGE_RING_DEPTH = "tracing_ring_depth"
GAUGE_ACTIVE = "tracing_active_traces"
GAUGE_ENABLED = "tracing_enabled"

# pod-trace span names in waterfall order (the bench stage waterfall and
# the SIGUSR2 renderer both order stages by this, unknown names last)
STAGE_ORDER = (
    "queue",
    "encode",
    "device",
    "readback",
    "guard",
    "assume",
    "bind",
    "ack",
    "outage.wait",
    "algo",
    "launch",
    "commit",
)

_tls = threading.local()


class _TraceRecord:
    __slots__ = (
        "trace_id",
        "kind",
        "key",
        "t0",
        "t1",
        "attrs",
        "spans",
        "events",
        "outcome",
    )

    def __init__(
        self,
        trace_id: str,
        kind: str,
        key: str,
        attrs: dict,
        t0: Optional[float] = None,
    ):
        self.trace_id = trace_id
        self.kind = kind
        self.key = key
        # t0 may be backdated (monotonic): a wave trace is minted only
        # once its launch succeeds, but its lifetime starts at cycle
        # entry — without this, its own encode span would predate it
        # (negative offsets) and total_s would omit encode+launch
        self.t0 = t0 if t0 is not None else time.monotonic()
        self.t1: Optional[float] = None
        self.attrs = attrs
        # (name, t0, t1, attrs-or-None) — atomic, never half-open
        self.spans: List[Tuple[str, float, float, Optional[dict]]] = []
        self.events: List[Tuple[float, str, str]] = []
        self.outcome = ""

    def total_s(self) -> float:
        end = self.t1 if self.t1 is not None else time.monotonic()
        return end - self.t0

    def stages(self) -> Dict[str, float]:
        """Per-stage wall, summed over same-named spans (a requeued pod
        legitimately has several `queue` spans)."""
        out: Dict[str, float] = {}
        for name, s0, s1, _a in self.spans:
            out[name] = out.get(name, 0.0) + (s1 - s0)
        return out

    def to_dict(self) -> dict:
        """JSON-renderable form; span times become offsets (ms) from the
        trace start so they are meaningful outside this process."""
        order = {n: i for i, n in enumerate(STAGE_ORDER)}
        return {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "key": self.key,
            "finished": self.t1 is not None,
            "outcome": self.outcome,
            "total_ms": round(self.total_s() * 1e3, 3),
            "attrs": dict(self.attrs),
            "stages_ms": {
                k: round(v * 1e3, 3)
                for k, v in sorted(
                    self.stages().items(),
                    key=lambda kv: order.get(kv[0], len(order)),
                )
            },
            "spans": [
                {
                    "name": name,
                    "start_ms": round((s0 - self.t0) * 1e3, 3),
                    "dur_ms": round((s1 - s0) * 1e3, 3),
                    **({"attrs": a} if a else {}),
                }
                for name, s0, s1, a in self.spans
            ],
            "events": [
                {
                    "at_ms": round((t - self.t0) * 1e3, 3),
                    "name": name,
                    **({"detail": detail} if detail else {}),
                }
                for t, name, detail in self.events
            ],
        }


class Tracer:
    """Process-global span pipeline: active traces, completed ring,
    store-side stamp ledger. All shared state under ONE leaf lock."""

    # spans/events per trace are capped: a pod stuck in a requeue storm
    # must not grow an unbounded span list
    MAX_SPANS = 96
    MAX_EVENTS = 64

    def __init__(
        self,
        ring_size: int = 1024,
        max_active: int = 65536,
        stamp_ledger_size: int = 4096,
    ):
        # one attribute test per entry point when disabled; flipped only
        # by set_enabled — a torn read is impossible for a bool
        self._enabled = os.environ.get("KTPU_TRACING", "1").lower() not in (  # graftlint: unguarded(single-writer bool flag, atomic read by design — same contract as lockgraph._enabled)
            "0",
            "false",
        )
        # named + Eraser-tracked: the ring enters the race-sanitizer
        # contract from day one (lock is a leaf — nothing acquired under)
        self._lock = named_lock("tracing.ring")
        self._active: Dict[str, _TraceRecord] = {}
        self._by_key: Dict[str, str] = {}  # pod key -> active trace id
        self._ring: deque = deque(maxlen=ring_size)
        self._store_ledger: deque = deque(maxlen=stamp_ledger_size)
        self._max_active = max_active
        # trace ids: one random per-process prefix + a counter — globally
        # unique like uuid4 but ~10x cheaper to mint on the admit path
        # (ids are minted per pod CREATE; a uuid4 per pod measurably taxes
        # a 4096-pod burst admit). next() on a count() is GIL-atomic.
        self._id_prefix = uuid.uuid4().hex[:8]
        self._id_counter = itertools.count(1)
        # counter/gauge deltas accumulate HERE (plain dict bumps under
        # the already-held trace lock) and publish to the metrics
        # registry in batches: per-op metrics.inc from the admit/finish
        # hot paths measurably taxed burst scheduling — the registry
        # lock is contended by the scheduler's own histogram observes
        # (measured: ~16% of a 6k-pod burst wall went to per-op
        # inc/set_gauge lock hops; batched, it is noise)
        self._counts: Dict[Tuple[str, str], int] = {}
        self._last_pub = 0.0  # graftlint: unguarded(single-float publish throttle; a torn read double-publishes at worst)
        self._pub_interval_s = 1.0

    # -- enable/disable -------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        from .metrics import metrics

        self._enabled = on
        metrics.set_gauge(GAUGE_ENABLED, 1.0 if on else 0.0)

    # -- trace lifecycle ------------------------------------------------------

    def start(
        self, kind: str, key: str, t0: Optional[float] = None, **attrs
    ) -> str:
        """Mint a trace; returns "" when disabled (every other entry
        point treats "" as a no-op id, so call sites stay unconditional).
        t0 (monotonic) backdates the trace start for records minted
        after their first span's interval began."""
        if not self._enabled:
            return ""
        seq = next(self._id_counter)
        trace_id = f"{self._id_prefix}{seq:08x}"
        rec = _TraceRecord(trace_id, kind, key, attrs, t0)
        with self._lock:
            if len(self._active) >= self._max_active:
                # evict the oldest active trace (dict preserves insertion
                # order) — bounded memory beats a complete tail under a
                # pathological backlog
                old_id, old = next(iter(self._active.items()))
                del self._active[old_id]
                if self._by_key.get(old.key) == old_id:
                    del self._by_key[old.key]
                self._bump_locked("dropped", "active_overflow")
            self._active[trace_id] = rec
            if kind == "pod":
                self._by_key[key] = trace_id
            self._bump_locked("started", kind)
        self._maybe_publish()
        return trace_id

    def finish(self, trace_id: str, outcome: str = "", **attrs) -> None:
        """Complete a trace: stamp t1, move it into the ring."""
        if not self._enabled or not trace_id:
            return
        with self._lock:
            rec = self._active.pop(trace_id, None)
            if rec is None:
                return
            if self._by_key.get(rec.key) == trace_id:
                del self._by_key[rec.key]
            rec.t1 = time.monotonic()
            rec.outcome = outcome
            if attrs:
                rec.attrs.update(attrs)
            self._ring.append(rec)
            self._bump_locked("completed", rec.kind)
        self._maybe_publish()

    def discard(self, trace_id: str) -> None:
        """Drop an active trace without completing it (pod deleted while
        queued — there is no lifecycle left to attribute)."""
        if not trace_id:
            return
        with self._lock:
            rec = self._active.pop(trace_id, None)
            if rec is not None:
                if self._by_key.get(rec.key) == trace_id:
                    del self._by_key[rec.key]
                self._bump_locked("dropped", "discarded")

    # -- span & event recording ----------------------------------------------

    def add_span(
        self, trace_id: str, name: str, t0: float, t1: float, **attrs
    ) -> None:
        """Record one closed span [t0, t1) (time.monotonic endpoints)."""
        if not self._enabled or not trace_id:
            return
        with self._lock:
            self._add_span_locked(trace_id, name, t0, t1, attrs or None)

    def add_spans(
        self, items: List[Tuple[str, str, float, float]]
    ) -> None:
        """Batch form — (trace_id, name, t0, t1) tuples, ONE lock
        acquisition for a whole wave's worth of per-pod spans."""
        if not self._enabled or not items:
            return
        with self._lock:
            for trace_id, name, t0, t1 in items:
                self._add_span_locked(trace_id, name, t0, t1, None)

    def add_span_many(
        self,
        trace_ids: List[str],
        name: str,
        t0: float,
        t1: float,
        **attrs,
    ) -> None:
        """The wave fan-in: one identical span recorded into N pod
        traces (e.g. the shared `device` interval) in one acquisition."""
        if not self._enabled or not trace_ids:
            return
        a = attrs or None
        with self._lock:
            for trace_id in trace_ids:
                self._add_span_locked(trace_id, name, t0, t1, a)

    def _add_span_locked(
        self,
        trace_id: str,
        name: str,
        t0: float,
        t1: float,
        attrs: Optional[dict],
    ) -> None:
        rec = self._active.get(trace_id)
        if rec is None or len(rec.spans) >= self.MAX_SPANS:
            return
        rec.spans.append((name, t0, t1, attrs))

    @contextmanager
    def span(self, trace_id: str, name: str, **attrs):
        """Inline span over a code region. MUST be used as a `with`
        statement (graftlint's tracing pass enforces it), so the span is
        closed on every exit path, exceptions included."""
        if not self._enabled or not trace_id:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add_span(trace_id, name, t0, time.monotonic(), **attrs)

    def event(self, trace_id: str, name: str, detail: str = "") -> None:
        """Point-in-time annotation on an active trace."""
        if not self._enabled or not trace_id:
            return
        t = time.monotonic()
        with self._lock:
            rec = self._active.get(trace_id)
            if rec is None or len(rec.events) >= self.MAX_EVENTS:
                return
            rec.events.append((t, name, detail[:160]))

    # -- cross-process store-side stamps --------------------------------------

    def stamp(self, trace_id: str, event: str, **attrs) -> None:
        """Store-side ledger entry under a (possibly foreign) trace id:
        the apply/fence record a scheduler's trace resolves to after the
        REST hop. Kept even when the id was minted in another process —
        that is the point."""
        if not self._enabled or not trace_id:
            return
        with self._lock:
            self._store_ledger.append(
                {
                    "trace_id": trace_id,
                    "event": event,
                    "t": time.monotonic(),
                    **attrs,
                }
            )
            self._bump_locked("stamp", event)
        self._maybe_publish()

    def stamps_for(self, trace_id: str) -> List[dict]:
        with self._lock:
            return [
                dict(s)
                for s in self._store_ledger
                if s["trace_id"] == trace_id
            ]

    # -- lookup / rendering ---------------------------------------------------

    def trace_for_pod(self, key: str) -> str:
        """The trace id owning pod `key` right now: the thread-local
        bind context (re-established from the REST header on the server
        side) wins; else the in-process active-trace index."""
        if not self._enabled:
            return ""
        ctx = getattr(_tls, "bind_ctx", None)
        if ctx:
            tid = ctx.get(key)
            if tid:
                return tid
        with self._lock:
            return self._by_key.get(key, "")

    def get(self, trace_id: str) -> Optional[dict]:
        """By-id lookup across active + ring, with any store-side stamps
        attached."""
        with self._lock:
            rec = self._active.get(trace_id)
            if rec is None:
                rec = next(
                    (r for r in self._ring if r.trace_id == trace_id), None
                )
            out = rec.to_dict() if rec is not None else None
            stamps = [
                dict(s)
                for s in self._store_ledger
                if s["trace_id"] == trace_id
            ]
        if out is None:
            if not stamps:
                return None
            # a foreign trace known only by its store stamps (the store
            # process's view of a scheduler-minted trace)
            out = {"trace_id": trace_id, "kind": "foreign", "spans": []}
        if stamps:
            out["store_stamps"] = stamps
        return out

    def slowest(self, n: int = 10, kind: str = "pod") -> List[dict]:
        with self._lock:
            recs = [r for r in self._ring if not kind or r.kind == kind]
            recs.sort(key=lambda r: r.total_s(), reverse=True)
            return [r.to_dict() for r in recs[:n]]

    def stage_stats(self, kind: str = "pod") -> Dict[str, dict]:
        """Aggregate per-stage durations over the ring's completed
        traces of `kind`: the bench stage waterfall's data source."""
        per_stage: Dict[str, List[float]] = {}
        with self._lock:
            recs = [r for r in self._ring if r.kind == kind]
            for r in recs:
                for name, dur in r.stages().items():
                    per_stage.setdefault(name, []).append(dur)
        out: Dict[str, dict] = {}
        for name, durs in per_stage.items():
            durs.sort()
            n = len(durs)
            out[name] = {
                "count": n,
                "total_s": round(sum(durs), 6),
                "p50_ms": round(durs[min(n // 2, n - 1)] * 1e3, 3),
                "p99_ms": round(
                    durs[min(int(0.99 * n), n - 1)] * 1e3, 3
                ),
            }
        order = {s: i for i, s in enumerate(STAGE_ORDER)}
        return dict(
            sorted(out.items(), key=lambda kv: order.get(kv[0], len(order)))
        )

    def render_lines(self, n: int = 5) -> List[str]:
        """The SIGUSR2 "traces" section: slowest-N completed pod traces
        as waterfall lines, plus ring/active occupancy."""
        with self._lock:
            active, ring = len(self._active), len(self._ring)
        lines = [
            f"  enabled: {self._enabled}  active: {active}  "
            f"ring: {ring}  (lookup: /debug/traces?id=<trace_id>)"
        ]
        for d in self.slowest(n):
            stages = "  ".join(
                f"{k}={v:.1f}ms" for k, v in d["stages_ms"].items()
            )
            lines.append(
                f"  {d['trace_id']} {d['key']} total={d['total_ms']:.1f}ms "
                f"[{d.get('outcome') or '?'}] {stages}"
            )
        return lines

    def _bump_locked(self, what: str, label: str) -> None:
        """Caller holds self._lock: accumulate one counter delta for the
        next batched publish (a plain dict bump — no registry lock)."""
        k = (what, label)
        self._counts[k] = self._counts.get(k, 0) + 1

    def _maybe_publish(self) -> None:
        """Time-throttled flush of accumulated deltas into the metrics
        registry (called OUTSIDE the trace lock)."""
        now = time.monotonic()
        if now - self._last_pub >= self._pub_interval_s:
            self._last_pub = now
            self.publish_gauges()

    def publish_gauges(self) -> None:
        """Flush accumulated counter deltas and refresh the occupancy
        gauges. Dump/scrape paths call this so a reader never sees stale
        tracing series; the hot paths only bump plain dicts and flush
        through here at most once per second."""
        with self._lock:
            depth, active = len(self._ring), len(self._active)
            deltas, self._counts = self._counts, {}
        from .metrics import metrics

        for (what, label), n in sorted(deltas.items()):
            by = float(n)
            if what == "started":
                metrics.inc(COUNTER_STARTED, {"kind": label}, by=by)
            elif what == "completed":
                metrics.inc(COUNTER_COMPLETED, {"kind": label}, by=by)
            elif what == "dropped":
                metrics.inc(COUNTER_DROPPED, {"reason": label}, by=by)
            elif what == "stamp":
                metrics.inc(COUNTER_STORE_STAMPS, {"outcome": label}, by=by)
        metrics.set_gauge(GAUGE_RING_DEPTH, float(depth))
        metrics.set_gauge(GAUGE_ACTIVE, float(active))
        metrics.set_gauge(GAUGE_ENABLED, 1.0 if self._enabled else 0.0)

    def reset(self) -> None:
        """Test/bench-window helper: drop every trace and stamp."""
        with self._lock:
            self._active.clear()
            self._by_key.clear()
            self._ring.clear()
            self._store_ledger.clear()
            self._counts.clear()


# lockset sanitizer (testing/lockgraph.py Eraser mode): the active
# table, pod-key index, completed ring, and store-stamp ledger are
# shared by scheduler/informer/bind-pool/REST-handler threads — all
# guarded by the one `tracing.ring` leaf lock, machine-checked in chaos
track_attrs(Tracer, "_active", "_by_key", "_ring", "_store_ledger", "_counts")


tracer = Tracer()  # process-global tracer (one ring per process)


# -- cross-process bind context ------------------------------------------------


@contextmanager
def bind_context(mapping: Dict[str, str]):
    """Establish pod-key -> trace-id context for the current thread (the
    REST /binding route enters this from the X-Trace-Context header so
    the store's stamps land under the scheduler-minted id)."""
    prev = getattr(_tls, "bind_ctx", None)
    _tls.bind_ctx = mapping
    try:
        yield
    finally:
        _tls.bind_ctx = prev


def stamp_bind(binding, event: str, **attrs) -> None:
    """Stamp a bind outcome for one Binding under whatever trace id owns
    the pod (thread-local context from the REST hop, or the in-process
    active index). No-op when nobody is tracing the pod."""
    key = f"{binding.pod_namespace}/{binding.pod_name}"
    tid = tracer.trace_for_pod(key)
    if tid:
        tracer.stamp(
            tid, event, key=key, node=getattr(binding, "target_node", ""),
            **attrs,
        )


def trace_for_binding(binding) -> str:
    """The trace id to attach to a /binding POST for this Binding."""
    return tracer.trace_for_pod(
        f"{binding.pod_namespace}/{binding.pod_name}"
    )


def health_lines() -> List[str]:
    """Tracing counters/gauges for the SIGUSR2 dump (covers the
    `tracing_` dump-required metric family)."""
    from .metrics import metrics

    tracer.publish_gauges()
    lines: List[str] = []
    for name, labels, value in metrics.snapshot_gauges("tracing_"):
        lines.append(metrics.format_series_line(name, labels, value))
    for name, labels, value in metrics.snapshot_counters("tracing_"):
        lines.append(metrics.format_series_line(name, labels, value))
    return lines
