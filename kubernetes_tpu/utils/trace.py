"""Step tracing with log-if-long semantics.

utiltrace equivalent (vendor/k8s.io/utils/trace/trace.go:55,64, used at
generic_scheduler.go:151-152): record named steps; emit only when total
duration exceeds the threshold — the slow-batch reporter for device cycles.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu")


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.monotonic()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.monotonic(), msg))

    def total(self) -> float:
        return time.monotonic() - self.start

    def log_if_long(self, threshold: float) -> bool:
        total = self.total()
        if total < threshold:
            return False
        parts = [f'"{self.name}" {self.fields} ({total*1000:.1f}ms):']
        last = self.start
        for t, msg in self.steps:
            parts.append(f"  +{(t - last)*1000:.1f}ms {msg}")
            last = t
        logger.warning("\n".join(parts))
        return True
