"""Device mesh + sharding specs for the scheduling data plane.

The scaling axis of a cluster scheduler is node count (SURVEY.md §5
"long-context" analogue): every [N, ·] snapshot tensor shards over the mesh's
"nodes" axis — the way sequence parallelism shards a context — and the
per-pod reductions (feasible-mask AND, score max/argmax, topology-domain
segment sums) become XLA collectives over ICI inserted by the SPMD
partitioner under jit. Pod batches and vocabulary-indexed metadata are
replicated (small).

Replaces the reference's process-parallel sharding story (informer fan-out +
16-goroutine ParallelizeUntil, SURVEY.md §2.3) with mesh parallelism.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.encoding import DeviceSnapshot

NODES_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    import numpy as np

    return Mesh(np.asarray(devices), (NODES_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _default_probe(device) -> bool:
    """One tiny put/get round-trip: the cheapest 'is this chip alive'
    signal that exercises both transfer directions."""
    import numpy as np

    x = jax.device_put(np.zeros(1, np.float32), device)
    return jax.device_get(x).shape == (1,)


def surviving_devices(devices: Sequence, probe=None) -> list:
    """Probe each device and return the ones that still respond — the
    device-loss ride-through's mesh-shrink input. `probe` is injectable
    so chaos tests can declare deaths deterministically."""
    probe = probe or _default_probe
    out = []
    for d in devices:
        try:
            if probe(d):
                out.append(d)
        except Exception:  # noqa: BLE001 — a dead device throws anything
            continue
    return out


def largest_pow2_prefix(devices: Sequence) -> list:
    """The usable shrink target: snapshot row counts are power-of-two
    padded, so the node axis only divides evenly over a power-of-two
    device count. 5 survivors → a 4-device mesh; 0 survivors → []."""
    n = len(devices)
    if n == 0:
        return []
    k = 1
    while k * 2 <= n:
        k *= 2
    return list(devices[:k])


def single_device_shardings(device) -> tuple:
    """Pin every snapshot field (and the replicated update scatters) to ONE
    specific device: the shrink-to-one-survivor target. `set_sharding(None,
    None)` would fall back to the JAX default device — which after a device
    loss may be exactly the dead chip."""
    from jax.sharding import SingleDeviceSharding

    one = SingleDeviceSharding(device)
    snap = DeviceSnapshot(**{f: one for f in DeviceSnapshot._fields})
    return snap, one


def snapshot_shardings(mesh: Mesh) -> DeviceSnapshot:
    """Sharding pytree for DeviceSnapshot: row-major arrays shard on the node
    axis; [T]-shaped eterm metadata replicates."""
    row = NamedSharding(mesh, P(NODES_AXIS))
    row2 = NamedSharding(mesh, P(NODES_AXIS, None))
    row3 = NamedSharding(mesh, P(NODES_AXIS, None, None))
    rep = replicated(mesh)
    return DeviceSnapshot(
        valid=row,
        unschedulable=row,
        allocatable=row2,
        requested=row2,
        nonzero_req=row2,
        label_vals=row2,
        label_numvals=row2,
        taint_key=row2,
        taint_val=row2,
        taint_effect=row2,
        sel_counts=row2,
        eterm_w=row2,
        eterm_topo_key=rep,
        eterm_kind=rep,
        port_counts=row2,
        image_bytes=row2,
        avoid=row2,
        prio_req=row3,
        band_prio=rep,
        pdb_blocked=row2,
        cost_milli=row,
        accel_class=row,
        energy_milli=row,
    )
