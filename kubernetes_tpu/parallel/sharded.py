"""Node-axis sharded scheduling step.

jit-compiles the same lattice kernel (ops/lattice.py) with the snapshot
sharded over the mesh's "nodes" axis. The SPMD partitioner turns:
  * the feasible-mask AND / per-node filter math → purely local work,
  * topology-domain segment-sums → local scatter-adds + psum over ICI
    (domain ids are global, so partial sums reduce across shards),
  * score max / argmax select → local max + pmax/all-gather of candidates,
  * the scan carry scatter (.at[idx].add) → a one-shard update.
This is the TPU equivalent of the reference's "shard informer fan-out +
goroutines per node chunk" (SURVEY.md §2.3 table) with ICI instead of
channels, and of its multi-host story (DCN) when the mesh spans hosts via
jax.distributed.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.encoding import DeviceSnapshot, PodBatch
from ..ops.lattice import BatchResult, make_schedule_batch_raw
from ..ops.templates import PairTable, TemplateBatch
from ..ops.wavelattice import WaveResult, make_wave_kernel
from .mesh import NODES_AXIS, replicated, snapshot_shardings


# -- device-loss classification + bounded retry ------------------------------


class DeviceLossError(RuntimeError):
    """Raised (or re-classified) when a kernel launch/readback failed
    because the device itself is gone or unreachable — as opposed to a
    program bug. The fault injector (testing/device_faults.py) raises this
    directly; real XLA surfaces jaxlib.XlaRuntimeError, matched below."""


# substrings (lowercased) that mark an XLA runtime error as device loss
# rather than a program error; deliberately conservative — a false
# negative costs a wave (requeued, zero pod loss), a false positive would
# retry/reshard on a genuine kernel bug and mask it
_DEVICE_LOSS_MARKERS = (
    "device unavailable",
    "device is unavailable",
    "device lost",
    "device not found",
    "unable to reach device",
    "failed to connect",
    "connection reset",
    "socket closed",
    "deadline exceeded",
    "data transfer failed",
    "halted",
    "unavailable:",
)


def is_device_loss_error(exc: BaseException) -> bool:
    if isinstance(exc, DeviceLossError):
        return True
    if type(exc).__name__ != "XlaRuntimeError" and not isinstance(
        exc, RuntimeError
    ):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


def device_retry_delay(attempts: int, base_delay_s: float = 0.05) -> float:
    """Jittered exponential backoff for device-loss retries — ONE policy
    shared by this helper and the scheduler's launch/serial retry loops
    (which can't use call_with_device_retry itself: each of their retries
    must re-encode/re-flush first)."""
    return base_delay_s * (2 ** attempts) * (1.0 + random.uniform(-0.3, 0.3))


def call_with_device_retry(
    fn: Callable,
    attempts: int,
    base_delay_s: float = 0.05,
    on_retry: Optional[Callable] = None,
):
    """Run fn(), retrying device-loss errors up to `attempts` times with
    jittered exponential backoff (a tunnel blip heals in tens of ms; a
    dead chip won't, and the caller's ride-through takes over). Only safe
    for repeatable calls — a launch that DONATED its inputs must re-flush
    before retrying and cannot use this helper."""
    n = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classifier filters
            if not is_device_loss_error(e) or n >= attempts:
                raise
            n += 1
            if on_retry is not None:
                on_retry(n, e)
            time.sleep(device_retry_delay(n, base_delay_s))


def shard_snapshot(snap: DeviceSnapshot, mesh: Mesh) -> DeviceSnapshot:
    """Place a snapshot onto the mesh with node-axis sharding. Row counts are
    capacity-padded powers of two, so they divide evenly over the mesh."""
    shardings = snapshot_shardings(mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s), snap, shardings
    )


@functools.lru_cache(maxsize=8)
def make_sharded_schedule_batch(
    v_cap: int, mesh: Mesh, hard_pod_affinity_weight: float = 1.0
):
    """The lattice kernel jitted with explicit in/out shardings over `mesh`.

    Everything except the snapshot is replicated; results (chosen rows,
    scores, counts) are replicated so the host reads them without gathers.
    The resolvable [P, N] mask stays sharded on N (it is only consulted for
    failed pods, host-side, via per-row gathers).
    """
    base = make_schedule_batch_raw(v_cap, hard_pod_affinity_weight)
    rep = replicated(mesh)
    in_shardings = (
        snapshot_shardings(mesh),
        PodBatch(*([rep] * len(PodBatch._fields))),
        rep,
        rep,
    )
    out_shardings = BatchResult(
        chosen=rep,
        score=rep,
        feasible_count=rep,
        resolvable=NamedSharding(mesh, P(None, NODES_AXIS)),
    )
    return jax.jit(base, in_shardings=in_shardings, out_shardings=out_shardings)


@functools.lru_cache(maxsize=32)
def make_sharded_wave_kernel(
    v_cap: int,
    m_cand: int,
    n_waves: int,
    hard_pod_affinity_weight: float,
    mesh: Mesh,
    use_pallas_fit: bool = False,
    score_refresh: bool = True,
    rtc_shape: tuple = None,
    has_pinned: bool = True,
):
    """The PRODUCTION wave kernel (ops/wavelattice.py) jitted with the
    snapshot sharded over the mesh's node axis.

    Same program as make_wave_kernel_jit — the SPMD partitioner turns its
    node-axis math into local work + ICI collectives:
      * per-template filter masks / score matrices [TPL, N]: purely local,
      * topology-domain segment-sums [J, V]: local partial sums + psum
        (domain ids are global across shards),
      * top-M candidate selection per template: local top-k + cross-shard
        merge (all-gather of the [TPL, M] candidates),
      * wave-loop conflict resolution on the POD axis: replicated (small),
      * occupancy commit scatters (.at[rows].add): routed to the owning
        shard.
    The donated snapshot stays sharded across batches, so consecutive
    batches chain on-device exactly like the single-chip path. This is the
    multi-chip analogue of the reference's 16-way node fan-out
    (generic_scheduler.go:490) with ICI collectives instead of goroutines.
    """
    from ..ops.wavelattice import DEFAULT_RTC_SHAPE

    base = make_wave_kernel(
        v_cap,
        m_cand,
        n_waves,
        hard_pod_affinity_weight,
        use_pallas_fit,
        score_refresh,
        rtc_shape or DEFAULT_RTC_SHAPE,
        has_pinned,
    )
    rep = replicated(mesh)
    snap_sh = snapshot_shardings(mesh)
    in_shardings = (
        snap_sh,
        TemplateBatch(
            tpl=PodBatch(*([rep] * len(PodBatch._fields))),
            pod_tpl=rep,
            pod_valid=rep,
            pod_name_row=rep,
            pod_prio=rep,
            pod_band=rep,
        ),
        PairTable(*([rep] * len(PairTable._fields))),
        rep,
        rep,
    )
    out_shardings = (
        snap_sh,
        WaveResult(
            chosen=rep,
            placed=rep,
            deferred=rep,
            feasible_count=rep,
            score=rep,
            resolvable_tpl=NamedSharding(mesh, P(None, NODES_AXIS)),
            feasible_tpl=NamedSharding(mesh, P(None, NODES_AXIS)),
        ),
    )
    return jax.jit(
        base,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(0,),
    )
