"""Mesh construction + node-axis sharded scheduling step."""

from .mesh import make_mesh, snapshot_shardings, replicated  # noqa: F401
from .sharded import make_sharded_schedule_batch, shard_snapshot  # noqa: F401
