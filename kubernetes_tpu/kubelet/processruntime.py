"""ProcessRuntime: real host-process supervision behind the CRI boundary.

The reference kubelet's runtime starts real containers through containerd
(pkg/kubelet/kuberuntime SyncPod -> CRI RunPodSandbox/CreateContainer/
StartContainer). This sandboxed build has no container engine, but it
does have a real OS: each container becomes a SUPERVISED HOST PROCESS in
its own process group with captured stdout/stderr, real exit codes, real
signals (SIGTERM -> grace -> SIGKILL, the reference's termination
sequence), and real per-pod CPU/RSS accounting read from /proc — the
"cgroup reads" of this environment. Everything the kubelet observes
(PLEG phase transitions, probes, logs, exec) comes from the live
processes, not bookkeeping.

A container without a command runs the pause-equivalent (a plain
``sleep``), so workloads that never specify commands behave like the
FakeRuntime's always-Running pods. Serve it across the framed CRI socket
with cri.wire.CRIServer for the full out-of-process topology.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api import objects as v1
from .runtime import PodRuntime

_PAUSE = ["/bin/sleep", "86400"]  # the pause container's role


class _Proc:
    __slots__ = ("name", "popen", "log_path")

    def __init__(self, name: str, popen, log_path: str):
        self.name = name
        self.popen = popen
        self.log_path = log_path


class _PodProcs:
    __slots__ = ("ip", "procs", "dir", "spec")

    def __init__(self, ip: str, procs: List[_Proc], d: str, spec: v1.Pod):
        self.ip = ip
        self.procs = procs
        self.dir = d
        self.spec = spec


class ProcessRuntime(PodRuntime):
    def __init__(self, ip_alloc, root_dir: str, grace_s: float = 2.0):
        self._pods: Dict[str, _PodProcs] = {}
        self._lock = threading.Lock()
        self._ip_alloc = ip_alloc
        self._root = root_dir
        self._grace_s = grace_s
        os.makedirs(root_dir, exist_ok=True)

    # -- lifecycle -----------------------------------------------------------

    def run_pod(self, pod: v1.Pod) -> str:
        key = pod.metadata.key
        pod_dir = os.path.join(self._root, key.replace("/", "_"))
        os.makedirs(pod_dir, exist_ok=True)
        procs: List[_Proc] = []
        try:
            for c in pod.spec.containers:
                # command overrides the (nonexistent) image entrypoint;
                # args-only becomes the argv — with no image metadata to
                # supply an entrypoint, failing loudly on a non-executable
                # args[0] beats silently running the pause sleep
                if c.command:
                    cmd = list(c.command) + list(c.args)
                elif c.args:
                    cmd = list(c.args)
                else:
                    cmd = _PAUSE
                log_path = os.path.join(pod_dir, f"{c.name or 'c'}.log")
                logf = open(log_path, "ab")
                try:
                    p = subprocess.Popen(
                        cmd,
                        stdout=logf,
                        stderr=subprocess.STDOUT,
                        cwd=pod_dir,
                        start_new_session=True,  # own pgid: kill takes the tree
                        env={**os.environ, "POD_NAME": pod.metadata.name,
                             "POD_NAMESPACE": pod.metadata.namespace},
                    )
                finally:
                    logf.close()  # child holds its own fd
                procs.append(_Proc(c.name or "c", p, log_path))
        except (OSError, FileNotFoundError):
            for pr in procs:  # partial start: kill what launched
                self._kill_proc(pr)
            raise
        ip = self._ip_alloc(pod.metadata.uid)
        with self._lock:
            self._pods[key] = _PodProcs(ip, procs, pod_dir, pod)
        return ip

    def _kill_proc(self, pr: _Proc) -> None:
        """SIGTERM the process group, grace, then SIGKILL (the kubelet's
        termination sequence)."""
        p = pr.popen
        if p.poll() is not None:
            return
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        try:
            p.wait(timeout=self._grace_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            p.wait(timeout=5)

    def kill_pod(self, pod_key: str) -> None:
        with self._lock:
            pp = self._pods.pop(pod_key, None)
        if pp is None:
            return
        for pr in pp.procs:
            self._kill_proc(pr)

    def restart_pod(self, pod_key: str) -> None:
        """Liveness remediation: kill + recreate the containers in place."""
        with self._lock:
            pp = self._pods.get(pod_key)
        if pp is None:
            return
        spec = pp.spec
        self.kill_pod(pod_key)
        self.run_pod(spec)

    # -- observation ---------------------------------------------------------

    def relist(self) -> Dict[str, str]:
        """PLEG from real process states: all containers exited 0 →
        Succeeded; any non-zero exit (with no survivor to restart) →
        Failed; otherwise Running."""
        out: Dict[str, str] = {}
        with self._lock:
            pods = dict(self._pods)
        for key, pp in pods.items():
            codes = [pr.popen.poll() for pr in pp.procs]
            if all(c is not None for c in codes):
                out[key] = (
                    v1.POD_SUCCEEDED
                    if all(c == 0 for c in codes)
                    else v1.POD_FAILED
                )
            else:
                out[key] = v1.POD_RUNNING
        return out

    def probe(self, pod_key: str, kind: str) -> bool:
        with self._lock:
            pp = self._pods.get(pod_key)
        if pp is None:
            return False
        return all(pr.popen.poll() is None for pr in pp.procs)

    def logs(self, pod_key: str, tail_lines: Optional[int] = None) -> str:
        with self._lock:
            pp = self._pods.get(pod_key)
        if pp is None:
            return ""
        chunks = []
        for pr in pp.procs:
            try:
                with open(pr.log_path, "r", errors="replace") as f:
                    chunks.append(f.read())
            except OSError:
                pass
        text = "".join(chunks)
        if tail_lines is not None:
            lines = text.splitlines()
            lines = lines[-tail_lines:] if tail_lines > 0 else []
            return "\n".join(lines) + ("\n" if lines else "")
        return text

    def exec(self, pod_key: str, command) -> str:
        return self.exec_status(pod_key, command)[0]

    def exec_status(self, pod_key: str, command) -> Tuple[str, int]:
        with self._lock:
            pp = self._pods.get(pod_key)
        if pp is None:
            raise KeyError(f"pod {pod_key} has no running sandbox")
        r = subprocess.run(
            list(command), cwd=pp.dir, capture_output=True, text=True,
            timeout=30,
        )
        return r.stdout + r.stderr, r.returncode

    # -- resource accounting (the /proc "cgroup read") -----------------------

    def pod_stats(self, pod_key: str) -> Tuple[float, int]:
        """(cpu_seconds, rss_bytes) summed over the pod's live processes,
        from /proc/<pid>/stat fields 14-15 (utime+stime) and statm RSS —
        the summary API the kubelet's eviction manager and metrics
        endpoints consume."""
        with self._lock:
            pp = self._pods.get(pod_key)
        if pp is None:
            return 0.0, 0
        hz = os.sysconf("SC_CLK_TCK")
        page = os.sysconf("SC_PAGE_SIZE")
        cpu = 0.0
        rss = 0
        for pr in pp.procs:
            pid = pr.popen.pid
            if pr.popen.poll() is not None:
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    parts = f.read().rsplit(") ", 1)[1].split()
                # post-comm fields: utime is index 11, stime 12 (absolute
                # fields 14-15, comm+state consumed by the rsplit)
                cpu += (int(parts[11]) + int(parts[12])) / hz
                with open(f"/proc/{pid}/statm") as f:
                    rss += int(f.read().split()[1]) * page
            except (OSError, IndexError, ValueError):
                continue
        return cpu, rss

    def running_pods(self) -> List[str]:
        with self._lock:
            return sorted(self._pods)
