from .kubelet import Kubelet, NodeAgentPool, make_node_object, NODE_LEASE_NS
from .runtime import ANN_FAIL, ANN_RUN_SECONDS, FakeRuntime, PodRuntime

__all__ = [
    "Kubelet",
    "NodeAgentPool",
    "make_node_object",
    "NODE_LEASE_NS",
    "FakeRuntime",
    "PodRuntime",
    "ANN_FAIL",
    "ANN_RUN_SECONDS",
]
