"""Container runtime boundary: the kubelet's CRI.

Reference: the kubelet drives pods through the CRI gRPC services
(pkg/kubelet/remote/remote_runtime.go:59, cri-api api.proto). Here the
boundary is a small in-process interface; FakeRuntime is the kubemark
hollow runtime (pkg/kubemark/hollow_kubelet.go:111-118 fake runtime/mounter)
with optional scripted completion so Job/controller tests can exercise
terminal phases.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..api import objects as v1

# annotations understood by FakeRuntime (test/kubemark scripting)
ANN_RUN_SECONDS = "kubelet.fake/run-seconds"  # complete after N seconds
ANN_FAIL = "kubelet.fake/fail"  # terminal phase Failed instead of Succeeded
ANN_READY_AFTER = "kubelet.fake/ready-after"  # readiness passes after N s
ANN_UNHEALTHY_AFTER = "kubelet.fake/unhealthy-after"  # liveness fails after N s


class PodRuntime:
    """What the kubelet needs from a runtime: start, kill, observe."""

    def run_pod(self, pod: v1.Pod) -> str:
        """Start the pod's sandbox+containers; returns the sandbox IP."""
        raise NotImplementedError

    def kill_pod(self, pod_key: str) -> None:
        raise NotImplementedError

    def relist(self) -> Dict[str, str]:
        """PLEG relist (pkg/kubelet/pleg/generic.go): pod_key -> phase for
        every pod the runtime knows; phases are POD_RUNNING / POD_SUCCEEDED
        / POD_FAILED."""
        raise NotImplementedError

    def probe(self, pod_key: str, kind: str) -> bool:
        """Health check backing the kubelet's prober (pkg/probe): kind is
        'liveness' or 'readiness'. Unknown pods fail both."""
        return pod_key in self.relist()

    def restart_pod(self, pod_key: str) -> None:
        """Liveness remediation: restart the pod's containers in place
        (kill + recreate, same sandbox — kuberuntime's container restart).
        Default: no-op."""

    def logs(self, pod_key: str, tail_lines: Optional[int] = None) -> str:
        """Container log text (the GetContainerLogs surface kubectl logs
        reaches through the kubelet). Default: empty."""
        return ""

    def exec(self, pod_key: str, command) -> str:
        """One-shot command execution in the pod's sandbox (the ExecSync
        surface kubectl exec reaches through the kubelet). Default:
        unsupported."""
        raise NotImplementedError("runtime does not support exec")

    def exec_status(self, pod_key: str, command) -> tuple:
        """(output, exit_code) — the full ExecSync contract. Runtimes
        that can observe the exit status override this; the default
        preserves exec()'s output-only behavior with code 0."""
        return self.exec(pod_key, command), 0


class _FakePod:
    __slots__ = ("ip", "started", "run_seconds", "fail", "ready_after", "unhealthy_after")

    def __init__(
        self,
        ip: str,
        run_seconds: Optional[float],
        fail: bool,
        ready_after: float = 0.0,
        unhealthy_after: Optional[float] = None,
    ):
        self.ip = ip
        self.started = time.monotonic()
        self.run_seconds = run_seconds
        self.fail = fail
        self.ready_after = ready_after
        self.unhealthy_after = unhealthy_after


class FakeRuntime(PodRuntime):
    """Instant-start fake: every pod is Running immediately; scripted pods
    complete after ANN_RUN_SECONDS."""

    def __init__(self, ip_alloc):
        self._pods: Dict[str, _FakePod] = {}
        self._lock = threading.Lock()
        self._ip_alloc = ip_alloc  # seed -> ip

    def run_pod(self, pod: v1.Pod) -> str:
        ann = pod.metadata.annotations
        run_s = ann.get(ANN_RUN_SECONDS)
        unh = ann.get(ANN_UNHEALTHY_AFTER)
        fp = _FakePod(
            ip=self._ip_alloc(pod.metadata.uid),
            run_seconds=float(run_s) if run_s is not None else None,
            fail=ann.get(ANN_FAIL, "") not in ("", "false"),
            ready_after=float(ann.get(ANN_READY_AFTER, "0")),
            unhealthy_after=float(unh) if unh is not None else None,
        )
        with self._lock:
            self._pods[pod.metadata.key] = fp
        return fp.ip

    def kill_pod(self, pod_key: str) -> None:
        with self._lock:
            self._pods.pop(pod_key, None)

    def probe(self, pod_key: str, kind: str) -> bool:
        now = time.monotonic()
        with self._lock:
            fp = self._pods.get(pod_key)
            if fp is None:
                return False
            age = now - fp.started
            if kind == "readiness":
                return age >= fp.ready_after
            return fp.unhealthy_after is None or age < fp.unhealthy_after

    def restart_pod(self, pod_key: str) -> None:
        # container restart resets the clocks: readiness warms up again and
        # an unhealthy-after script becomes unhealthy again after the delay
        with self._lock:
            fp = self._pods.get(pod_key)
            if fp is not None:
                fp.started = time.monotonic()

    def relist(self) -> Dict[str, str]:
        now = time.monotonic()
        out: Dict[str, str] = {}
        with self._lock:
            for key, fp in self._pods.items():
                if (
                    fp.run_seconds is not None
                    and now - fp.started >= fp.run_seconds
                ):
                    out[key] = v1.POD_FAILED if fp.fail else v1.POD_SUCCEEDED
                else:
                    out[key] = v1.POD_RUNNING
        return out

    def logs(self, pod_key: str, tail_lines: Optional[int] = None) -> str:
        """Synthesized container log (the hollow runtime's stand-in for
        real container output): lifecycle lines with timestamps."""
        now = time.monotonic()
        with self._lock:
            fp = self._pods.get(pod_key)
            if fp is None:
                return ""
            age = now - fp.started
            lines = [
                f"[fake-runtime] pod {pod_key} sandbox started (ip {fp.ip})",
                f"[fake-runtime] uptime {age:.1f}s",
            ]
            if fp.run_seconds is not None:
                outcome = "fail" if fp.fail else "succeed"
                lines.append(
                    f"[fake-runtime] scripted to {outcome} after "
                    f"{fp.run_seconds:.1f}s"
                )
        if tail_lines is not None:
            lines = lines[-tail_lines:] if tail_lines > 0 else []
        return "\n".join(lines) + "\n" if lines else ""

    def exec(self, pod_key: str, command) -> str:
        """ExecSync against the fake sandbox: a few built-in commands give
        tests something real to assert on; everything else echoes."""
        with self._lock:
            fp = self._pods.get(pod_key)
        if fp is None:
            raise KeyError(f"pod {pod_key} has no running sandbox")
        cmd = list(command)
        if cmd[:1] == ["hostname"]:
            return pod_key.rsplit("/", 1)[-1] + "\n"
        if cmd[:1] == ["ip"]:
            return fp.ip + "\n"
        if cmd[:1] == ["echo"]:
            return " ".join(cmd[1:]) + "\n"
        return f"[fake-runtime] exec: {' '.join(cmd)}\n"
