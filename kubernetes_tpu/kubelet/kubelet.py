"""Node agent: pod sync loop + status + heartbeats.

Reference: pkg/kubelet/kubelet.go — Run(:1401) starts the sync machinery,
syncLoop(:1820)/syncLoopIteration(:1894) select over config updates, PLEG
events, and housekeeping; syncPod(:1482) drives the runtime. This build
keeps the same event structure but multiplexes many nodes onto shared
threads (NodeAgentPool) so a 5k-node hollow cluster is cheap — one watch
stream feeds per-node Kubelet objects that share one code path whether the
runtime is fake (kubemark) or real.

Heartbeats follow the nodelease KEP: renew a Lease every interval and keep
the NodeStatus Ready condition fresh (pkg/kubelet/nodelease; nodelifecycle
watches both).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..api import objects as v1
from ..client.apiserver import Conflict, NotFound, NotPrimary
from ..client.leaderelection import Lease
from ..runtime.consensus import DegradedWrites
from ..runtime.watch import BOOKMARK
from ..utils.metrics import metrics
from .runtime import FakeRuntime, PodRuntime

logger = logging.getLogger("kubernetes_tpu.kubelet")

NODE_LEASE_NS = "kube-node-lease"

# status/condition writes dropped while the store is degraded: counted
# skips, never raises — the next sync/housekeeping cycle retries, and
# failing the shared pool threads over a read-only store would turn one
# outage into a fleet-wide kubelet stall (PR-3 ride-through discipline,
# enforced tree-wide by graftlint's degraded-write pass)
COUNTER_DEGRADED_SKIPS = "kubelet_degraded_write_skips_total"  # {write}


def skip_degraded_write(write: str) -> None:
    metrics.inc(COUNTER_DEGRADED_SKIPS, {"write": write})


def make_node_object(
    name: str,
    cpu: str = "4",
    memory: str = "32Gi",
    pods: int = 110,
    labels: Optional[dict] = None,
) -> v1.Node:
    return v1.Node(
        metadata=v1.ObjectMeta(name=name, namespace="", labels=labels or {}),
        spec=v1.NodeSpec(),
        status=v1.NodeStatus(
            capacity={"cpu": cpu, "memory": memory, "pods": pods},
            allocatable={"cpu": cpu, "memory": memory, "pods": pods},
            conditions=[v1.NodeCondition(type=v1.NODE_READY, status="True")],
        ),
    )


class _ProbeWorker:
    """One (pod, kind) prober (pkg/kubelet/prober/worker.go): tracks
    consecutive results against the probe's thresholds."""

    __slots__ = ("probe", "started", "last_run", "succ", "fail", "result")

    def __init__(self, probe: v1.Probe, now: float):
        self.probe = probe
        self.started = now
        self.last_run = float("-inf")
        self.succ = 0
        self.fail = 0
        # readiness starts False (pod not Ready until the probe passes);
        # liveness starts True (a container is assumed live until proven
        # otherwise) — prober/worker.go initialValue
        self.result: bool = False

    def due(self, now: float) -> bool:
        if now - self.started < self.probe.initial_delay_seconds:
            return False
        return now - self.last_run >= self.probe.period_seconds

    def observe(self, ok: bool, now: float) -> bool:
        """Record one probe result; returns the (possibly flipped)
        effective result."""
        self.last_run = now
        if ok:
            self.succ += 1
            self.fail = 0
            if self.succ >= self.probe.success_threshold:
                self.result = True
        else:
            self.fail += 1
            self.succ = 0
            if self.fail >= self.probe.failure_threshold:
                self.result = False
        return self.result


class Kubelet:
    """One node's agent. Thread-free: the pool (or a test) drives it via
    handle_pod_event / housekeeping / heartbeat."""

    def __init__(
        self,
        server,
        node_name: str,
        runtime: PodRuntime,
        host_ip: Optional[str] = None,
        device_manager=None,
    ):
        self.server = server
        self.node_name = node_name
        self.runtime = runtime
        self.host_ip = host_ip  # the node's address (same for all its pods)
        # optional device-plugin manager (devicemanager.DeviceManager):
        # allocates plugin devices at pod admission, frees on termination,
        # and surfaces extended-resource capacity into NodeStatus
        self.device_manager = device_manager
        self._device_generation = -1
        # optional volume manager (volumemanager.VolumeManager): PVC pods
        # wait for attach+mount before the sandbox starts
        self.volume_manager = None
        # optional node-pressure eviction manager (eviction.EvictionManager)
        self.eviction_manager = None
        # optional container manager (cm.ContainerManager): reserved-
        # resource accounting — allocatable = capacity - reservations is
        # posted to NodeStatus so the scheduler packs against it
        self.container_manager = None
        self._allocatable_synced = False
        self._wait_volumes: Dict[str, v1.Pod] = {}  # parked on mounts
        self._known: Dict[str, str] = {}  # pod key -> last posted phase
        self._specs: Dict[str, v1.Pod] = {}  # pod key -> last seen spec
        # prober bookkeeping (pkg/kubelet/prober): (key, kind) -> worker
        self._probes: Dict[tuple, _ProbeWorker] = {}

    # -- pod lifecycle (syncPod, kubelet.go:1482) ----------------------------

    def handle_pod_event(self, ev_type: str, pod: v1.Pod) -> None:
        if pod.spec.node_name != self.node_name:
            return
        key = pod.metadata.key
        if ev_type == "DELETED":
            self.runtime.kill_pod(key)
            self._known.pop(key, None)
            self._forget_probes(key)
            self._wait_volumes.pop(key, None)
            if self.device_manager is not None:
                self.device_manager.free_pod(key)
            if self.volume_manager is not None:
                self.volume_manager.forget_pod(key)
            return
        if pod.status.phase in (v1.POD_SUCCEEDED, v1.POD_FAILED):
            # terminal: runtime resources are reclaimed, status stands
            self.runtime.kill_pod(key)
            self._known[key] = pod.status.phase
            self._forget_probes(key)
            if self.device_manager is not None:
                self.device_manager.free_pod(key)
            return
        self._specs[key] = pod
        if key not in self._known:
            if self.device_manager is not None:
                # device admission BEFORE the sandbox starts (the manager's
                # Allocate ordering in kubelet admission, manager.go)
                try:
                    self.device_manager.allocate_pod(pod)
                except Exception as e:
                    self._post_admission_failure(pod, str(e))
                    self._known[key] = v1.POD_FAILED
                    return
            if self.volume_manager is not None:
                # WaitForAttachAndMount: a PVC pod parks until its volumes
                # are set up; housekeeping reconciles + retries
                self.volume_manager.note_pod(pod)
                if not self.volume_manager.mounts_ready(pod):
                    self.volume_manager.reconcile()
                if not self.volume_manager.mounts_ready(pod):
                    self._wait_volumes[key] = pod
                    return
                self._wait_volumes.pop(key, None)
            self._ensure_images(pod)
            ip = self.runtime.run_pod(pod)
            self._known[key] = v1.POD_RUNNING
            # phase and the initial Ready verdict land in ONE status write:
            # posting them separately opens a window where Running exists
            # with no Ready condition and pod_is_ready() defaults to True —
            # endpoints would briefly publish a warming-up pod
            self._post_status(
                pod,
                v1.POD_RUNNING,
                ip,
                ready=self._probe_of(pod, "readiness") is None,
            )
            self._start_probes(pod, post_ready=False)

    def _ensure_images(self, pod: v1.Pod) -> None:
        """Image-pull step before the sandbox starts (the reference's
        imageManager.EnsureImageExists per container): honored when the
        runtime exposes an ImageService (pull_image/image_status —
        RemoteRuntime does); policy Always re-pulls, IfNotPresent (the
        default) pulls only when the image is absent, Never skips."""
        pull = getattr(self.runtime, "pull_image", None)
        if pull is None:
            return
        status = getattr(self.runtime, "image_status", None)
        for c in pod.spec.containers:
            if not c.image:
                continue
            policy = c.image_pull_policy or "IfNotPresent"
            if policy == "Never":
                continue
            try:
                if (
                    policy == "IfNotPresent"
                    and status is not None
                    and status(c.image) is not None
                ):
                    continue
                pull(c.image)
            except Exception:
                logger.exception("image pull %s failed", c.image)

    def housekeeping(self) -> None:
        """PLEG relist → post phase transitions (pleg/generic.go 1s relist)."""
        for key, phase in self.runtime.relist().items():
            if self._known.get(key) == phase:
                continue
            ns, _, name = key.partition("/")
            try:
                pod = self.server.get("pods", ns, name)
            except NotFound:
                self.runtime.kill_pod(key)
                self._known.pop(key, None)
                self._forget_probes(key)
                continue
            self._known[key] = phase
            if phase in (v1.POD_SUCCEEDED, v1.POD_FAILED):
                self.runtime.kill_pod(key)
                self._forget_probes(key)
                if self.device_manager is not None:
                    self.device_manager.free_pod(key)
                self._post_status(pod, phase, None)
        self.sync_device_capacity()
        self.sync_node_allocatable()
        if self.eviction_manager is not None:
            try:
                self.eviction_manager.synchronize()
            except Exception:
                logger.exception("eviction manager pass failed")
        if self.volume_manager is not None:
            self.volume_manager.reconcile()
            for key, pod in list(self._wait_volumes.items()):
                if self.volume_manager.mounts_ready(pod):
                    del self._wait_volumes[key]
                    self.handle_pod_event("ADDED", pod)
        self.publish_pod_stats()
        self.run_probes()

    # cAdvisor-analogue sampling state: pod key -> (cpu_seconds, mono_ts)
    _stat_samples: Optional[Dict[str, tuple]] = None
    _stats_published_at: float = float("-inf")
    stats_publish_interval_s: float = 10.0  # metrics-server resolution

    def publish_pod_stats(self) -> None:
        """Real usage -> the metrics pipeline: when the runtime measures
        actual processes (ProcessRuntime.pod_stats reading /proc), derive
        a CPU rate between housekeeping passes and publish it on the pod
        as the metrics.kubernetes.io annotations the metrics.k8s.io
        endpoints and HPA consume (the cAdvisor → summary API flow).
        Throttled to the metrics-server's ~10 s resolution: at the 1 s
        PLEG cadence an unthrottled pass would add a write + a MODIFIED
        fan-out to every pod informer per active pod per second."""
        stats_fn = getattr(self.runtime, "pod_stats", None)
        if stats_fn is None:
            return
        now = time.monotonic()
        if now - self._stats_published_at < self.stats_publish_interval_s:
            return
        self._stats_published_at = now
        if self._stat_samples is None:
            self._stat_samples = {}
        for key in list(self._known):
            cpu_s, rss = stats_fn(key)
            prev = self._stat_samples.get(key)
            self._stat_samples[key] = (cpu_s, now)
            if prev is None:
                continue
            prev_cpu, prev_ts = prev
            dt = now - prev_ts
            if dt <= 0:
                continue
            millicores = max(0, int((cpu_s - prev_cpu) / dt * 1000))
            ns, _, name = key.partition("/")

            def mutate(p, mc=millicores, mem=rss):
                ann = p.metadata.annotations
                new_cpu, new_mem = f"{mc}m", str(mem)
                if (
                    ann.get("metrics.kubernetes.io/cpu-usage") == new_cpu
                    and ann.get("metrics.kubernetes.io/memory-usage") == new_mem
                ):
                    return None  # no-op write suppression
                ann["metrics.kubernetes.io/cpu-usage"] = new_cpu
                ann["metrics.kubernetes.io/memory-usage"] = new_mem
                return p

            try:
                self.server.guaranteed_update("pods", ns, name, mutate)
            except NotFound:
                self._stat_samples.pop(key, None)
            except DegradedWrites:
                skip_degraded_write("pod_stats")
        for key in list(self._stat_samples):
            if key not in self._known:
                del self._stat_samples[key]

    # -- probes (pkg/kubelet/prober) -----------------------------------------

    @staticmethod
    def _probe_of(pod: v1.Pod, kind: str):
        """Effective pod-level probe: the runtime health channel is
        pod-scoped (one sandbox verdict per kind), so multiple containers'
        probes collapse to the STRICTEST combination — shortest period,
        longest warmup, fewest failures tolerated, most successes
        required. (The reference ANDs per-container results; with a
        pod-scoped runtime the strictest-config collapse is the closest
        sound equivalent.)"""
        attr = "readiness_probe" if kind == "readiness" else "liveness_probe"
        probes = [getattr(c, attr) for c in pod.spec.containers if getattr(c, attr)]
        if not probes:
            return None
        if len(probes) == 1:
            return probes[0]
        return v1.Probe(
            period_seconds=min(p.period_seconds for p in probes),
            initial_delay_seconds=max(p.initial_delay_seconds for p in probes),
            failure_threshold=min(p.failure_threshold for p in probes),
            success_threshold=max(p.success_threshold for p in probes),
        )

    def _start_probes(
        self, pod: v1.Pod, now: Optional[float] = None, post_ready: bool = True
    ) -> None:
        now = now if now is not None else time.monotonic()
        key = pod.metadata.key
        for kind in ("readiness", "liveness"):
            p = self._probe_of(pod, kind)
            if p is not None:
                w = _ProbeWorker(p, now)
                if kind == "liveness":
                    w.result = True
                self._probes[(key, kind)] = w
        if post_ready:
            # restart path: the restarted container warms up again, so a
            # probe-bearing pod drops out of Ready; probe-less pods are
            # Ready whenever Running (status_manager)
            self._post_ready(pod, (key, "readiness") not in self._probes)

    def _forget_probes(self, key: str) -> None:
        self._specs.pop(key, None)
        self._probes.pop((key, "readiness"), None)
        self._probes.pop((key, "liveness"), None)

    def run_probes(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.monotonic()
        for (key, kind), w in list(self._probes.items()):
            if self._known.get(key) != v1.POD_RUNNING or not w.due(now):
                continue
            pod = self._specs.get(key)
            if pod is None:
                continue
            before = w.result
            after = w.observe(self.runtime.probe(key, kind), now)
            if kind == "readiness":
                if after != before:
                    self._post_ready(pod, after)
            elif before and not after:
                # liveness remediation: restart the containers in place
                # (restart policy Always semantics), count it, and reset
                # both probes — the restarted container warms up again
                self.runtime.restart_pod(key)
                self._bump_restart_count(pod)
                self._start_probes(pod, now)

    def _post_ready(self, pod: v1.Pod, ready: bool) -> None:
        status = "True" if ready else "False"

        def mutate(p):
            if p.status.phase in (v1.POD_SUCCEEDED, v1.POD_FAILED):
                return None
            for c in p.status.conditions:
                if c.type == v1.COND_POD_READY:
                    if c.status == status:
                        return None
                    c.status = status
                    c.last_transition_time = time.time()
                    return p
            p.status.conditions.append(
                v1.PodCondition(type=v1.COND_POD_READY, status=status)
            )
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("pod_ready")

    def _bump_restart_count(self, pod: v1.Pod) -> None:
        names = [c.name or f"c{i}" for i, c in enumerate(pod.spec.containers)]

        def mutate(p):
            if not p.status.container_statuses:
                p.status.container_statuses = [
                    v1.ContainerStatus(name=n, ready=False) for n in names
                ]
            for cs in p.status.container_statuses:
                cs.restart_count += 1
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("restart_count")

    def _post_status(
        self,
        pod: v1.Pod,
        phase: str,
        ip: Optional[str],
        ready: Optional[bool] = None,
    ) -> None:
        def mutate(p):
            if p.status.phase in (v1.POD_SUCCEEDED, v1.POD_FAILED):
                # never regress a terminal phase (a stale watch snapshot
                # racing a completed pod must not flip it back to Running)
                return None
            if (
                p.status.phase == phase
                and (ip is None or p.status.pod_ip == ip)
                and ready is None
            ):
                return None
            p.status.phase = phase
            if p.status.start_time is None:
                p.status.start_time = time.time()
            if ip is not None:
                p.status.pod_ip = ip
                p.status.host_ip = self.host_ip or ip
            if ready is not None:
                status = "True" if ready else "False"
                for c in p.status.conditions:
                    if c.type == v1.COND_POD_READY:
                        c.status = status
                        break
                else:
                    p.status.conditions.append(
                        v1.PodCondition(type=v1.COND_POD_READY, status=status)
                    )
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("pod_status")

    # -- heartbeats (pkg/kubelet/nodelease) ----------------------------------

    # lease-renewal retry budget on retryable 503s (DegradedWrites): a
    # transient degraded blip must not silently drop the renewal — that is
    # how a control-plane outage turns into false NotReady → eviction.
    # Both attempt- AND time-bounded; a persistent in-process outage bails
    # even faster via write-gate introspection. NOTE the budget only gates
    # BETWEEN attempts: a RESTClient burns its own Retry-After sleeps
    # (~3 s at defaults) INSIDE each call before DegradedWrites surfaces,
    # which this loop cannot shorten — a REST-backed pool that must not
    # stall its serial heartbeat sweep should wire the heartbeat path
    # with degraded_retries=0 and let this loop own the retry policy.
    heartbeat_retries: int = 3
    heartbeat_retry_budget_s: float = 0.5

    def heartbeat(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()

        def renew(lease):
            lease.renew_time = now
            return lease

        delay = 0.05
        deadline = time.monotonic() + self.heartbeat_retry_budget_s
        for attempt in range(self.heartbeat_retries + 1):
            try:
                self.server.guaranteed_update(
                    "leases", NODE_LEASE_NS, self.node_name, renew
                )
                return
            except (NotFound, Conflict):
                return
            except NotPrimary:
                # fenced ex-primary: permanent for that endpoint — never
                # retry against it (callers re-point the pool at the new
                # leader); dropping the renewal must not kill the SHARED
                # heartbeat thread
                metrics.inc("kubelet_heartbeat_renewals_dropped_total")
                return
            except DegradedWrites:
                gate = getattr(self.server, "write_gate", None)
                if (
                    attempt >= self.heartbeat_retries
                    or time.monotonic() >= deadline
                    or (gate is not None and getattr(gate, "degraded", False))
                ):
                    # store still read-only: this renewal is dropped (the
                    # next beat retries); nodelifecycle's partial-disruption
                    # threshold covers the fleet-wide staleness this causes
                    metrics.inc("kubelet_heartbeat_renewals_dropped_total")
                    return
                metrics.inc("kubelet_heartbeat_retries_total")
                time.sleep(delay + random.uniform(0, delay))
                delay = min(delay * 2, 0.2)

    def _post_admission_failure(self, pod: v1.Pod, message: str) -> None:
        """UnexpectedAdmissionError (the reference's device-admission
        failure phase): the pod fails on this node; a controller replaces
        it and the scheduler tries elsewhere."""

        def mutate(p):
            p.status.phase = v1.POD_FAILED
            p.status.reason = "UnexpectedAdmissionError"
            p.status.message = message
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("admission_failure")

    def sync_device_capacity(self) -> None:
        """Surface plugin resources into NodeStatus capacity/allocatable
        (manager.go GetCapacity -> node status setters). Cheap no-op until
        the manager's device set actually changes."""
        dm = self.device_manager
        if dm is None or dm.generation == self._device_generation:
            return
        gen = dm.generation
        caps = dm.capacities()

        def mutate(node):
            changed = False
            for res, cnt in caps.items():
                if node.status.capacity.get(res) != cnt:
                    node.status.capacity[res] = cnt
                    node.status.allocatable[res] = cnt
                    changed = True
            return node if changed else None

        try:
            self.server.guaranteed_update("nodes", "", self.node_name, mutate)
            self._device_generation = gen
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("device_capacity")

    def sync_node_allocatable(self) -> None:
        """Post allocatable = capacity - reservations (container_manager's
        Node Allocatable math; cm/container_manager_linux.go) once — the
        reservations are static for the kubelet's lifetime."""
        cm = self.container_manager
        if cm is None or self._allocatable_synced:
            return

        def mutate(node):
            alloc = cm.node_allocatable(node.status.capacity)
            if node.status.allocatable == alloc:
                return None
            node.status.allocatable = alloc
            return node

        try:
            self.server.guaranteed_update("nodes", "", self.node_name, mutate)
            self._allocatable_synced = True
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("node_allocatable")

    def post_ready_condition(self, now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()

        def mutate(node):
            for c in node.status.conditions:
                if c.type == v1.NODE_READY:
                    c.status = "True"
                    c.last_heartbeat_time = now
                    return node
            node.status.conditions.append(
                v1.NodeCondition(type=v1.NODE_READY, status="True")
            )
            return node

        try:
            self.server.guaranteed_update("nodes", "", self.node_name, mutate)
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("ready_condition")


class NodeAgentPool:
    """Run many Kubelets on shared threads: one pod-watch dispatcher, one
    heartbeat loop, one housekeeping (PLEG) loop. The kubemark trick of
    multiplexing hollow nodes in-process — with the REAL kubelet sync code."""

    def __init__(
        self,
        server,
        heartbeat_interval: float = 10.0,
        housekeeping_interval: float = 0.5,
        runtime_factory: Optional[Callable[[str], PodRuntime]] = None,
    ):
        self.server = server
        self.heartbeat_interval = heartbeat_interval
        self.housekeeping_interval = housekeeping_interval
        self.kubelets: Dict[str, Kubelet] = {}
        self._runtime_factory = runtime_factory or self._default_runtime
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        # the node-side service dataplane (kube-proxy-lite): one shared
        # Proxier per pool — the table has no per-node state in this build,
        # mirroring kubemark's HollowProxy sharing one iptables interface
        from ..proxy import Proxier

        self.proxy = Proxier(server)

    @staticmethod
    def _default_runtime(node_name: str) -> PodRuntime:
        from ..kubemark.hollow_node import _fake_pod_ip

        return FakeRuntime(_fake_pod_ip)

    # -- membership ----------------------------------------------------------

    def add_node(self, name: str, register: bool = True, **node_kw) -> Kubelet:  # graftlint: degraded-ok(node registration must surface: the caller owns the retry — silently skipping would hand out a Kubelet for a node the store never saw)
        if register:
            self.server.create("nodes", make_node_object(name, **node_kw))
            try:
                self.server.create(
                    "leases",
                    Lease(
                        metadata=v1.ObjectMeta(name=name, namespace=NODE_LEASE_NS),
                        holder_identity=name,
                        lease_duration_seconds=40.0,
                        renew_time=time.time(),
                    ),
                )
            except Exception:
                pass
        from ..kubemark.hollow_node import _fake_pod_ip

        kl = Kubelet(
            self.server,
            name,
            self._runtime_factory(name),
            host_ip=_fake_pod_ip(name),
        )
        with self._lock:
            self.kubelets[name] = kl
        # surface the node's logs/exec to the apiserver (kubectl logs/exec
        # hop); remote clients (joined pools) have no provider registry
        providers = getattr(self.server, "log_providers", None)
        if providers is not None:
            providers[name] = kl.runtime.logs
        execs = getattr(self.server, "exec_providers", None)
        if execs is not None:
            execs[name] = kl.runtime.exec
        return kl

    def remove_node(self, name: str) -> None:
        """Stop the node's agent (the node 'dies'; object stays for
        nodelifecycle to notice the missed heartbeats)."""
        with self._lock:
            self.kubelets.pop(name, None)
        for attr in ("log_providers", "exec_providers"):
            providers = getattr(self.server, attr, None)
            if providers is not None:
                providers.pop(name, None)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for target, name in (
            (self._watch_loop, "kubelet-watch"),
            (self._heartbeat_loop, "kubelet-heartbeat"),
            (self._housekeeping_loop, "kubelet-pleg"),
        ):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        self.proxy.start()

    def stop(self) -> None:
        self._stop.set()
        self.proxy.stop()

    # -- shared loops --------------------------------------------------------

    def _kubelet_for(self, pod: v1.Pod) -> Optional[Kubelet]:
        with self._lock:
            return self.kubelets.get(pod.spec.node_name)

    def _watch_loop(self) -> None:
        from ..client.apiserver import list_and_watch

        def dispatch(ev_type: str, pod: v1.Pod) -> None:
            kl = self._kubelet_for(pod)
            if kl is None:
                return
            try:
                kl.handle_pod_event(ev_type, pod)
            except Exception:
                # a status write 503ing against a degraded store (or any
                # per-pod failure) must not kill the SHARED watch loop —
                # the PLEG relist reconciles the missed transition
                logger.exception(
                    "pod event %s for %s failed on node %s",
                    ev_type, pod.metadata.key, pod.spec.node_name,
                )

        def seed(pods):
            for pod in pods:
                dispatch("ADDED", pod)

        watcher = list_and_watch(self.server, "pods", seed)
        while not self._stop.is_set():
            ev = watcher.get(timeout=0.2)
            if ev is None or ev.type == BOOKMARK:
                # bookmarks are rv-only progress notifies from the watch
                # cache — no pod state to sync
                continue
            dispatch(ev.type, ev.object)
        watcher.stop()

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            now = time.time()
            with self._lock:
                kls = list(self.kubelets.values())
            for kl in kls:
                if self._stop.is_set():
                    return
                try:
                    kl.heartbeat(now)
                except Exception:
                    # one node's renewal failure (unexpected transport
                    # error, fenced store, ...) must not kill the SHARED
                    # heartbeat thread for the whole pool — that would
                    # manufacture the mass-NotReady cascade this layer
                    # exists to prevent
                    logger.exception("heartbeat failed for %s", kl.node_name)
            self._stop.wait(self.heartbeat_interval)

    def _housekeeping_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                kls = list(self.kubelets.values())
            for kl in kls:
                if self._stop.is_set():
                    return
                try:
                    kl.housekeeping()
                except Exception:
                    logger.exception("housekeeping failed for %s", kl.node_name)
            self._stop.wait(self.housekeeping_interval)
