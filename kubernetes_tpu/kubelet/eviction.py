"""Kubelet node-pressure eviction + QoS classes.

Reference: pkg/kubelet/eviction/eviction_manager.go (synthesize node
conditions from resource-pressure signals, rank and evict victims) and
pkg/apis/core/v1/helper/qos (QoS class derivation). The hollow runtime has
no real memory counters, so the pressure signal is injectable: by default
it is committed memory (sum of pod requests, the only truth this build
has) against allocatable; tests and real runtimes can supply live usage.

Victim ranking mirrors rankMemoryPressure: BestEffort pods first, then
Burstable pods whose usage (requests here) exceeds their requests, then
the rest by descending priority-then-usage — Guaranteed and critical pods
last. An eviction posts the pod's Failed status with reason Evicted, sets
the node's MemoryPressure condition, and taints the node
(node.kubernetes.io/memory-pressure, the scheduler's TaintToleration keeps
new pods away until pressure clears).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

from ..api import objects as v1
from ..api.resources import MEMORY, parse_quantity
from ..client.apiserver import Conflict, NotFound
from ..runtime.consensus import DegradedWrites
from .kubelet import skip_degraded_write

logger = logging.getLogger("kubernetes_tpu.kubelet.eviction")

QOS_GUARANTEED = "Guaranteed"
QOS_BURSTABLE = "Burstable"
QOS_BEST_EFFORT = "BestEffort"

MEMORY_PRESSURE_TAINT = "node.kubernetes.io/memory-pressure"
COND_MEMORY_PRESSURE = "MemoryPressure"


def qos_class(pod: v1.Pod) -> str:
    """pkg/apis/core/v1/helper/qos GetPodQOSClass: Guaranteed iff every
    container has cpu+memory requests == limits (and they are set);
    BestEffort iff no container sets any request or limit; else Burstable."""
    containers = list(pod.spec.containers) + list(pod.spec.init_containers)
    if not any(c.requests or c.limits for c in containers):
        return QOS_BEST_EFFORT
    for c in containers:
        for res in ("cpu", "memory"):
            req = c.requests.get(res)
            lim = c.limits.get(res)
            if req is None or lim is None or str(req) != str(lim):
                return QOS_BURSTABLE
    return QOS_GUARANTEED


class EvictionManager:
    """One per node. `usage_fn(pod) -> bytes` supplies per-pod memory
    usage (default: the pod's memory request — committed memory is the
    only signal a hollow runtime has); available memory is
    allocatable - sum(usage)."""

    def __init__(
        self,
        server,
        node_name: str,
        memory_threshold_bytes: int = 100 << 20,  # evict when avail < 100Mi
        usage_fn: Optional[Callable[[v1.Pod], int]] = None,
        grace_period_s: float = 0.0,
    ):
        self.server = server
        self.node_name = node_name
        self.threshold = memory_threshold_bytes
        self.usage_fn = usage_fn or self._requested_memory
        self.grace_period_s = grace_period_s
        self.evictions = 0  # counter (tests/metrics)
        self._pressure_since: Optional[float] = None

    @staticmethod
    def _requested_memory(pod: v1.Pod) -> int:
        req = v1.compute_pod_resource_request(pod)
        return int(req.get(MEMORY, 0))

    def _node_pods(self) -> List[v1.Pod]:
        pods, _ = self.server.list("pods")
        return [
            p
            for p in pods
            if p.spec.node_name == self.node_name
            and p.status.phase not in (v1.POD_SUCCEEDED, v1.POD_FAILED)
            and p.metadata.deletion_timestamp is None
        ]

    def _allocatable_memory(self) -> int:
        try:
            node = self.server.get("nodes", "", self.node_name)
        except NotFound:
            return 0
        return int(parse_quantity(node.status.allocatable.get("memory", 0)))

    def synchronize(self) -> List[str]:
        """One manager pass (eviction_manager.go synchronize): measure,
        set/clear the pressure condition+taint, evict at most ONE victim
        per pass (the reference's one-eviction-per-interval pacing).
        Returns evicted pod keys."""
        pods = self._node_pods()
        used = {p.metadata.key: self.usage_fn(p) for p in pods}
        available = self._allocatable_memory() - sum(used.values())
        under_pressure = available < self.threshold
        now = time.monotonic()
        if under_pressure and self._pressure_since is None:
            self._pressure_since = now
        if not under_pressure:
            self._pressure_since = None
        self._set_pressure(under_pressure)
        if not under_pressure:
            return []
        if now - self._pressure_since < self.grace_period_s:
            return []
        victims = self._rank(pods, used)
        if not victims:
            return []
        victim = victims[0]
        self._evict(victim, available)
        return [victim.metadata.key]

    def _rank(self, pods: List[v1.Pod], used) -> List[v1.Pod]:
        """rankMemoryPressure: (exceeds-requests, qos, priority, usage).
        BestEffort always "exceeds" (request 0); Guaranteed within its
        requests ranks last with critical priorities."""

        def key(p: v1.Pod):
            req = int(
                v1.compute_pod_resource_request(p).get(MEMORY, 0)
            )
            u = used.get(p.metadata.key, 0)
            exceeds = u > req or qos_class(p) == QOS_BEST_EFFORT
            return (
                not exceeds,  # exceeders first
                p.priority,  # lower priority first
                -u,  # biggest usage first
            )

        return sorted(pods, key=key)

    def _evict(self, pod: v1.Pod, available: int) -> None:
        self.evictions += 1
        logger.warning(
            "evicting %s: node %s available memory %d < threshold %d",
            pod.metadata.key,
            self.node_name,
            available,
            self.threshold,
        )

        def mutate(p):
            p.status.phase = v1.POD_FAILED
            p.status.reason = "Evicted"
            p.status.message = (
                "The node was low on resource: memory. "
                f"Available: {available}, threshold: {self.threshold}."
            )
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("evict")

    def _set_pressure(self, pressure: bool) -> None:
        status = "True" if pressure else "False"

        def mutate(node):
            changed = False
            for c in node.status.conditions:
                if c.type == COND_MEMORY_PRESSURE:
                    if c.status != status:
                        c.status = status
                        c.last_transition_time = time.time()
                        changed = True
                    break
            else:
                node.status.conditions.append(
                    v1.NodeCondition(type=COND_MEMORY_PRESSURE, status=status)
                )
                changed = True
            has_taint = any(
                t.key == MEMORY_PRESSURE_TAINT for t in node.spec.taints
            )
            if pressure and not has_taint:
                node.spec.taints = list(node.spec.taints) + [
                    v1.Taint(MEMORY_PRESSURE_TAINT, "", v1.TAINT_NO_SCHEDULE)
                ]
                changed = True
            elif not pressure and has_taint:
                node.spec.taints = [
                    t
                    for t in node.spec.taints
                    if t.key != MEMORY_PRESSURE_TAINT
                ]
                changed = True
            return node if changed else None

        try:
            self.server.guaranteed_update("nodes", "", self.node_name, mutate)
        except (NotFound, Conflict):
            pass
        except DegradedWrites:
            skip_degraded_write("memory_pressure")
