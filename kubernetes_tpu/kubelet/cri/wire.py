"""CRI transport: length-prefixed protobuf frames over a unix socket.

The reference kubelet dials the runtime's socket and speaks gRPC
(pkg/kubelet/remote/remote_runtime.go:59 grpc.DialContext). This build
keeps the identical architecture — protobuf request/response messages
across a real process boundary on a local socket — with a minimal framed
RPC instead of gRPC (no grpc python in the image):

    frame := u32(len(method)) method u32(len(payload)) payload
    reply := u8(status) u32(len(payload)) payload     status 0=ok, 1=error

Server side: ``CRIServer`` exposes any PodRuntime as a RuntimeService.
Client side: ``RemoteRuntime`` is a PodRuntime backed by the socket, so
the UNCHANGED kubelet sync loop drives pods through the wire
(kubelet/kubelet.py never knows which side of the boundary it's on).
"""

from __future__ import annotations

import logging
import os
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Tuple

from ...api import objects as v1
from ..runtime import ANN_FAIL, ANN_RUN_SECONDS, PodRuntime
from . import api_pb2 as pb

logger = logging.getLogger("kubernetes_tpu.kubelet.cri")

_U32 = struct.Struct(">I")

_STATE_TO_PHASE = {
    pb.SANDBOX_READY: v1.POD_RUNNING,
    pb.SANDBOX_NOTREADY: v1.POD_RUNNING,
    pb.SANDBOX_SUCCEEDED: v1.POD_SUCCEEDED,
    pb.SANDBOX_FAILED: v1.POD_FAILED,
}
_PHASE_TO_STATE = {
    v1.POD_RUNNING: pb.SANDBOX_READY,
    v1.POD_SUCCEEDED: pb.SANDBOX_SUCCEEDED,
    v1.POD_FAILED: pb.SANDBOX_FAILED,
}


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, method: bytes, payload: bytes) -> None:
    sock.sendall(_U32.pack(len(method)) + method + _U32.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Tuple[bytes, bytes]:
    (mlen,) = _U32.unpack(_read_exact(sock, 4))
    method = _read_exact(sock, mlen)
    (plen,) = _U32.unpack(_read_exact(sock, 4))
    return method, _read_exact(sock, plen)


# ---------------------------------------------------------------------------
# server: PodRuntime -> RuntimeService
# ---------------------------------------------------------------------------


class CRIServer:
    """Serve a PodRuntime over a unix socket (the containerd side)."""

    def __init__(self, runtime: PodRuntime, socket_path: str):
        self.runtime = runtime
        self.socket_path = socket_path
        self._srv: Optional[socketserver.ThreadingUnixStreamServer] = None
        # sandbox id <-> pod bookkeeping (the runtime keys by pod key)
        self._meta: Dict[str, pb.PodSandboxMetadata] = {}
        self._ips: Dict[str, str] = {}
        self._images: Dict[str, int] = {}  # ImageService store: name -> bytes
        self._lock = threading.Lock()

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        method, payload = _recv_frame(self.request)
                        status, resp = outer._dispatch(method.decode(), payload)
                        self.request.sendall(
                            bytes([status]) + _U32.pack(len(resp)) + resp
                        )
                except (ConnectionError, OSError):
                    pass

        self._srv = socketserver.ThreadingUnixStreamServer(
            self.socket_path, Handler
        )
        self._srv.daemon_threads = True
        threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="cri-server"
        ).start()

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    # -- RuntimeService ------------------------------------------------------

    def _dispatch(self, method: str, payload: bytes) -> Tuple[int, bytes]:
        try:
            handler = getattr(self, f"_h_{method}", None)
            if handler is None:
                raise ValueError(f"unimplemented CRI method {method!r}")
            return 0, handler(payload)
        except Exception as e:  # error frames carry a StatusError
            err = pb.StatusError(message=f"{type(e).__name__}: {e}")
            return 1, err.SerializeToString()

    def _h_Version(self, payload: bytes) -> bytes:
        return pb.VersionResponse(
            runtime_name="kubernetes-tpu-fake", runtime_version="v1"
        ).SerializeToString()

    def _h_RunPodSandbox(self, payload: bytes) -> bytes:
        req = pb.RunPodSandboxRequest.FromString(payload)
        md = req.config.metadata
        pod = v1.Pod(
            metadata=v1.ObjectMeta(
                name=md.name,
                namespace=md.namespace,
                uid=md.uid,
                labels=dict(req.config.labels),
                annotations=dict(req.config.annotations),
            ),
            spec=v1.PodSpec(
                containers=[
                    v1.Container(
                        name=c.name,
                        image=c.image,
                        command=list(c.command),
                        args=list(c.args),
                    )
                    for c in req.config.containers
                ]
            ),
        )
        ip = self.runtime.run_pod(pod)
        sandbox_id = pod.metadata.key
        with self._lock:
            self._meta[sandbox_id] = pb.PodSandboxMetadata(
                name=md.name, namespace=md.namespace, uid=md.uid
            )
            self._ips[sandbox_id] = ip
        return pb.RunPodSandboxResponse(
            pod_sandbox_id=sandbox_id, ip=ip
        ).SerializeToString()

    def _h_StopPodSandbox(self, payload: bytes) -> bytes:
        req = pb.StopPodSandboxRequest.FromString(payload)
        self.runtime.kill_pod(req.pod_sandbox_id)
        return pb.StopPodSandboxResponse().SerializeToString()

    def _h_RemovePodSandbox(self, payload: bytes) -> bytes:
        req = pb.RemovePodSandboxRequest.FromString(payload)
        self.runtime.kill_pod(req.pod_sandbox_id)
        with self._lock:
            self._meta.pop(req.pod_sandbox_id, None)
            self._ips.pop(req.pod_sandbox_id, None)
        return pb.RemovePodSandboxResponse().SerializeToString()

    def _h_ListPodSandbox(self, payload: bytes) -> bytes:
        phases = self.runtime.relist()
        resp = pb.ListPodSandboxResponse()
        with self._lock:
            for key, phase in phases.items():
                sb = resp.items.add()
                sb.id = key
                sb.state = _PHASE_TO_STATE.get(phase, pb.SANDBOX_NOTREADY)
                sb.ip = self._ips.get(key, "")
                if key in self._meta:
                    sb.metadata.CopyFrom(self._meta[key])
        return resp.SerializeToString()

    def _h_ExecSync(self, payload: bytes) -> bytes:
        # exceptions (no sandbox / unsupported) become error frames in
        # _dispatch; a COMPLETED-but-failed command reports its real exit
        # code (the reference's ExecSyncResponse.exit_code)
        req = pb.ExecSyncRequest.FromString(payload)
        out, code = self.runtime.exec_status(
            req.pod_sandbox_id, list(req.command)
        )
        return pb.ExecSyncResponse(
            stdout=out.encode(), exit_code=code
        ).SerializeToString()

    def _h_ContainerLogs(self, payload: bytes) -> bytes:
        req = pb.ContainerLogsRequest.FromString(payload)
        text = self.runtime.logs(
            req.pod_sandbox_id,
            tail_lines=req.tail_lines or None,
        )
        return pb.ContainerLogsResponse(data=text.encode()).SerializeToString()

    # -- ImageService (subset) -----------------------------------------------
    # the runtime side keeps the image store (real runtimes track pulled
    # layers); this server holds it since PodRuntime has no image state

    def _h_PullImage(self, payload: bytes) -> bytes:
        req = pb.PullImageRequest.FromString(payload)
        name = req.image.image
        with self._lock:
            self._images[name] = 10_000_000  # nominal layer size
        return pb.PullImageResponse(image_ref=f"sha256:{name}").SerializeToString()

    def _h_ListImages(self, payload: bytes) -> bytes:
        resp = pb.ListImagesResponse()
        with self._lock:
            for name, size in sorted(self._images.items()):
                img = resp.images.add()
                img.id = f"sha256:{name}"
                img.repo_tags.append(name)
                img.size_bytes = size
        return resp.SerializeToString()

    def _h_ImageStatus(self, payload: bytes) -> bytes:
        req = pb.ImageStatusRequest.FromString(payload)
        resp = pb.ImageStatusResponse()
        with self._lock:
            size = self._images.get(req.image.image)
        if size is not None:
            resp.image.id = f"sha256:{req.image.image}"
            resp.image.repo_tags.append(req.image.image)
            resp.image.size_bytes = size
        return resp.SerializeToString()

    def _h_RemoveImage(self, payload: bytes) -> bytes:
        req = pb.RemoveImageRequest.FromString(payload)
        with self._lock:
            self._images.pop(req.image.image, None)
        return pb.RemoveImageResponse().SerializeToString()

    def _h_ImageFsInfo(self, payload: bytes) -> bytes:
        with self._lock:
            used = sum(self._images.values())
        return pb.ImageFsInfoResponse(
            used_bytes=used, capacity_bytes=100 * 1024 * 1024 * 1024
        ).SerializeToString()


# ---------------------------------------------------------------------------
# client: RuntimeService -> PodRuntime
# ---------------------------------------------------------------------------


class RemoteRuntime(PodRuntime):
    """PodRuntime over the CRI socket (the kubelet side,
    remote_runtime.go's role). One connection, calls serialized — the
    kubelet's sync loop and PLEG take turns like the reference's
    single-client gRPC channel."""

    def __init__(self, socket_path: str, timeout: float = 30.0):
        self.socket_path = socket_path
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self.timeout)
            s.connect(self.socket_path)
            self._sock = s
        return self._sock

    def _call(self, method: str, req) -> bytes:
        with self._lock:
            try:
                sock = self._conn()
                _send_frame(sock, method.encode(), req.SerializeToString())
                status = _read_exact(sock, 1)[0]
                (plen,) = _U32.unpack(_read_exact(sock, 4))
                payload = _read_exact(sock, plen)
            except (ConnectionError, OSError):
                # crash-only runtime: drop the connection, surface the error
                if self._sock is not None:
                    self._sock.close()
                    self._sock = None
                raise
        if status != 0:
            err = pb.StatusError.FromString(payload)
            raise RuntimeError(f"CRI {method}: {err.message}")
        return payload

    def version(self) -> str:
        resp = pb.VersionResponse.FromString(
            self._call("Version", pb.VersionRequest())
        )
        return f"{resp.runtime_name}/{resp.runtime_version}"

    # -- PodRuntime ----------------------------------------------------------

    def run_pod(self, pod: v1.Pod) -> str:
        cfg = pb.PodSandboxConfig(
            metadata=pb.PodSandboxMetadata(
                name=pod.metadata.name,
                namespace=pod.metadata.namespace,
                uid=pod.metadata.uid,
            )
        )
        for k, val in pod.metadata.labels.items():
            cfg.labels[k] = val
        for k, val in pod.metadata.annotations.items():
            if k in (ANN_RUN_SECONDS, ANN_FAIL):
                cfg.annotations[k] = val
        for c in pod.spec.containers:
            cc = cfg.containers.add()
            cc.name = c.name
            cc.image = c.image
            cc.command.extend(c.command)
            cc.args.extend(c.args)
        resp = pb.RunPodSandboxResponse.FromString(
            self._call("RunPodSandbox", pb.RunPodSandboxRequest(config=cfg))
        )
        return resp.ip

    def kill_pod(self, pod_key: str) -> None:
        self._call(
            "StopPodSandbox", pb.StopPodSandboxRequest(pod_sandbox_id=pod_key)
        )
        self._call(
            "RemovePodSandbox",
            pb.RemovePodSandboxRequest(pod_sandbox_id=pod_key),
        )

    def relist(self) -> Dict[str, str]:
        resp = pb.ListPodSandboxResponse.FromString(
            self._call("ListPodSandbox", pb.ListPodSandboxRequest())
        )
        return {
            sb.id: _STATE_TO_PHASE.get(sb.state, v1.POD_RUNNING)
            for sb in resp.items
        }

    def exec(self, pod_key: str, command) -> str:
        return self.exec_status(pod_key, command)[0]

    def exec_status(self, pod_key: str, command) -> Tuple[str, int]:
        resp = pb.ExecSyncResponse.FromString(
            self._call(
                "ExecSync",
                pb.ExecSyncRequest(
                    pod_sandbox_id=pod_key, command=list(command)
                ),
            )
        )
        return resp.stdout.decode(errors="replace"), resp.exit_code

    def logs(self, pod_key: str, tail_lines: Optional[int] = None) -> str:
        resp = pb.ContainerLogsResponse.FromString(
            self._call(
                "ContainerLogs",
                pb.ContainerLogsRequest(
                    pod_sandbox_id=pod_key, tail_lines=tail_lines or 0
                ),
            )
        )
        return resp.data.decode(errors="replace")

    # -- ImageService ---------------------------------------------------------

    def pull_image(self, image: str) -> str:
        resp = pb.PullImageResponse.FromString(
            self._call(
                "PullImage",
                pb.PullImageRequest(image=pb.ImageSpec(image=image)),
            )
        )
        return resp.image_ref

    def list_images(self) -> Dict[str, int]:
        resp = pb.ListImagesResponse.FromString(
            self._call("ListImages", pb.ListImagesRequest())
        )
        return {img.repo_tags[0]: img.size_bytes for img in resp.images}

    def image_status(self, image: str) -> Optional[str]:
        resp = pb.ImageStatusResponse.FromString(
            self._call(
                "ImageStatus",
                pb.ImageStatusRequest(image=pb.ImageSpec(image=image)),
            )
        )
        return resp.image.id or None

    def remove_image(self, image: str) -> None:
        self._call(
            "RemoveImage",
            pb.RemoveImageRequest(image=pb.ImageSpec(image=image)),
        )

    def image_fs_info(self) -> Tuple[int, int]:
        resp = pb.ImageFsInfoResponse.FromString(
            self._call("ImageFsInfo", pb.ImageFsInfoRequest())
        )
        return resp.used_bytes, resp.capacity_bytes

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
