"""CRI: the kubelet <-> runtime process boundary (protobuf over a unix
socket — reference cri-api + kubelet/remote)."""

from .wire import CRIServer, RemoteRuntime  # noqa: F401
