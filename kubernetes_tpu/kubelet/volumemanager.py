"""Kubelet volume manager: the node side of the volume path.

Reference: pkg/kubelet/volumemanager/volume_manager.go — a desired-state
populator (what the node's pods need mounted) and a reconciler
(WaitForAttach → MountDevice → SetUp per pod; TearDown/UnmountDevice when
pods go away), reporting VolumesInUse on the node status so the
attach-detach controller never detaches a volume the node still uses
(the safe-detach contract).

This build's "mount" is bookkeeping (there is no real filesystem, exactly
like kubemark's hollow kubelet faking the mounter), but the state machine
and its ordering are real:

  pod needs PVC -> PVC bound to PV -> VolumeAttachment(pv, node) attached
      -> device "mounted" (node-global) -> pod volume "set up"
  pod gone -> pod volume torn down -> last user unmounts the device
      -> volumes_in_use drops the PV -> the AD controller may detach

The kubelet defers starting a PVC-bearing pod until its volumes are set
up, and housekeeping retries — the reference's pod-worker wait on
volumemanager.WaitForAttachAndMount.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set

from ..api import objects as v1
from ..client.apiserver import NotFound
from ..runtime.consensus import DegradedWrites
from .kubelet import skip_degraded_write

logger = logging.getLogger("kubernetes_tpu.kubelet.volumemanager")


class VolumeManager:
    def __init__(self, server, node_name: str, csi=None):
        self.server = server
        self.node_name = node_name
        # CSI boundary (kubelet/csi.py): csi-backed PVs additionally drive
        # the external driver's node service around these transitions
        # (reference csi_client.go); None = no CSI support on this node
        self.csi = csi
        self._lock = threading.Lock()
        # desired: pod key -> set of PV names
        self._desired: Dict[str, Set[str]] = {}
        # actual: PV -> set of pod keys it is set up for (device-mounted
        # while non-empty)
        self._mounted: Dict[str, Set[str]] = {}
        self._last_reported: Optional[List[str]] = None

    # -- desired state populator --------------------------------------------

    def note_pod(self, pod: v1.Pod) -> None:
        """Track a pod's PV needs (desired_state_of_world populator)."""
        pvs = self._pod_pvs(pod)
        with self._lock:
            if pvs:
                self._desired[pod.metadata.key] = pvs
            else:
                self._desired.pop(pod.metadata.key, None)

    def forget_pod(self, pod_key: str) -> None:
        with self._lock:
            self._desired.pop(pod_key, None)

    def _pod_pvs(self, pod: v1.Pod) -> Set[str]:
        out: Set[str] = set()
        for vol in pod.spec.volumes:
            if not vol.persistent_volume_claim:
                continue
            try:
                pvc = self.server.get(
                    "persistentvolumeclaims",
                    pod.metadata.namespace,
                    vol.persistent_volume_claim,
                )
            except NotFound:
                continue
            if pvc.spec.volume_name:
                out.add(pvc.spec.volume_name)
        return out

    # -- reconciler ----------------------------------------------------------

    def reconcile(self) -> None:
        """One reconciler pass (reconciler.go reconcile()): mount what is
        desired and attached, tear down what is no longer desired, then
        report volumes_in_use. CSI-backed PVs drive the external driver
        around each transition; the driver calls run OUTSIDE the lock (a
        slow/dead driver must not block the populator), and a failed call
        leaves the pair un-mounted for the next pass to retry."""
        with self._lock:
            desired = {k: set(v) for k, v in self._desired.items()}
        attached = self._attached_pvs()
        setups: List = []  # (pod_key, pv)
        teardowns: List = []  # (pod_key, pv, last_user)
        with self._lock:
            for pod_key, pvs in desired.items():
                for pv in pvs:
                    users = self._mounted.get(pv, set())
                    if pod_key not in users and pv in attached:
                        setups.append((pod_key, pv))
            for pv, users in self._mounted.items():
                stale = [k for k in users if pv not in desired.get(k, ())]
                for n, pod_key in enumerate(stale, start=1):
                    teardowns.append(
                        (pod_key, pv, n == len(stale) == len(users))
                    )
        done_setups = []
        for pod_key, pv in setups:
            src = self._csi_source(pv)
            if src is not None:
                if self.csi is None or not self.csi.has_driver(src.driver):
                    continue  # no driver yet: stays pending, retried
                try:
                    self.csi.stage_and_publish(src, pod_key)
                except Exception as e:  # CSIError and transport faults
                    logger.warning("csi setup %s/%s: %s", pv, pod_key, e)
                    continue
            done_setups.append((pod_key, pv))
        done_teardowns = []
        for pod_key, pv, last_user in teardowns:
            src = self._csi_source(pv)
            if src is not None and self.csi is not None:
                if not self.csi.unpublish(src, pod_key, last_user):
                    # driver fault: keep the pair mounted so the next
                    # pass re-issues the teardown (no driver-side leak)
                    continue
            done_teardowns.append((pod_key, pv))
        with self._lock:
            for pod_key, pv in done_setups:
                # MountDevice (first user) + SetUp
                self._mounted.setdefault(pv, set()).add(pod_key)
            for pod_key, pv in done_teardowns:
                users = self._mounted.get(pv)
                if users is not None:
                    users.discard(pod_key)  # TearDown
                    if not users:
                        del self._mounted[pv]  # UnmountDevice
            in_use = sorted(
                set(self._mounted)
                | {pv for pvs in desired.values() for pv in pvs}
            )
        self._report_volumes_in_use(in_use)

    def _csi_source(self, pv_name: str):
        """The PV's csi source, or None for in-tree volumes."""
        try:
            pv = self.server.get("persistentvolumes", "", pv_name)
        except NotFound:
            return None
        return pv.spec.csi

    def _attached_pvs(self) -> Set[str]:
        try:
            attachments, _ = self.server.list("volumeattachments")
        except Exception:
            return set()
        return {
            a.spec.pv_name
            for a in attachments
            if a.spec.node_name == self.node_name and a.status.attached
        }

    def _report_volumes_in_use(self, in_use: List[str]) -> None:
        """node.status.volumesInUse (VolumeManager.GetVolumesInUse → node
        status updater): the AD controller's safe-detach input."""
        if in_use == self._last_reported:
            return

        def mutate(node):
            if node.status.volumes_in_use == in_use:
                return None
            node.status.volumes_in_use = list(in_use)
            node.status.volumes_attached = sorted(self._attached_pvs())
            return node

        try:
            self.server.guaranteed_update("nodes", "", self.node_name, mutate)
            self._last_reported = list(in_use)
        except NotFound:
            pass
        except DegradedWrites:
            skip_degraded_write("volumes_in_use")

    # -- the pod-worker wait (WaitForAttachAndMount) -------------------------

    def mounts_ready(self, pod: v1.Pod) -> bool:
        """True when every PV the pod needs is set up for it (or it needs
        none). The kubelet blocks pod start on this."""
        pvs = self._pod_pvs(pod)
        if not pvs:
            return True
        key = pod.metadata.key
        with self._lock:
            return all(key in self._mounted.get(pv, ()) for pv in pvs)

    def mounted_for(self, pod_key: str) -> List[str]:
        with self._lock:
            return sorted(
                pv for pv, users in self._mounted.items() if pod_key in users
            )
