"""Kubelet device-plugin manager with topology hints.

Reference: pkg/kubelet/cm/devicemanager/manager.go:1 (plugin registration,
ListAndWatch device streams, Allocate, checkpointing) and topology_hints.go
(per-resource NUMA-affinity hints merged by the topology manager). The
architecture is preserved — device plugins are SEPARATE PROCESSES speaking
an RPC protocol over unix sockets — with the same framed transport the CRI
boundary uses (kubelet/cri/wire.py) and JSON payloads instead of gRPC:

  plugin -> kubelet (registry socket):
      Register     {"resource": "tpu.dev/chip", "endpoint": "/path.sock",
                    "devices": [{"id": "d0", "healthy": true, "topology": 0}]}
      Update       {"resource": ..., "devices": [...]}   (ListAndWatch push)
  kubelet -> plugin (the plugin's own endpoint socket, dialed back):
      Allocate     {"device_ids": ["d0", "d1"]}  -> {"envs": {...}, ...}

For a TPU-native stack the "topology" id is the chip's locality domain
(NUMA node / host / ICI pod-slice): aligned allocations keep a pod's chips
on one interconnect domain, which is the scheduling decision that matters
for collective bandwidth.

Allocations checkpoint to a JSON file (device_plugin_state) and restore on
kubelet restart, like the reference's checkpoint manager.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger("kubernetes_tpu.kubelet.devicemanager")

_U32 = struct.Struct(">I")


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, method: str, payload: dict) -> None:
    m = method.encode()
    p = json.dumps(payload).encode()
    sock.sendall(_U32.pack(len(m)) + m + _U32.pack(len(p)) + p)


def _recv_frame(sock: socket.socket) -> Tuple[str, dict]:
    (mlen,) = _U32.unpack(_read_exact(sock, 4))
    method = _read_exact(sock, mlen).decode()
    (plen,) = _U32.unpack(_read_exact(sock, 4))
    return method, json.loads(_read_exact(sock, plen) or b"{}")


def _reply(sock: socket.socket, status: int, payload: dict) -> None:
    p = json.dumps(payload).encode()
    sock.sendall(bytes([status]) + _U32.pack(len(p)) + p)


def _read_reply(sock: socket.socket) -> dict:
    status = _read_exact(sock, 1)[0]
    (plen,) = _U32.unpack(_read_exact(sock, 4))
    payload = json.loads(_read_exact(sock, plen) or b"{}")
    if status != 0:
        raise RuntimeError(payload.get("error", "device plugin error"))
    return payload


@dataclass
class Device:
    id: str
    healthy: bool = True
    topology: int = 0  # locality domain (NUMA node / ICI slice)


@dataclass
class _Endpoint:
    """One registered plugin resource."""

    resource: str
    endpoint: str  # plugin's own socket path (dialed back for Allocate)
    devices: Dict[str, Device] = field(default_factory=dict)


class TopologyHint:
    """A set of locality domains that can satisfy a request; preferred
    when it spans exactly one domain (topologymanager's bitmask hints)."""

    __slots__ = ("domains", "preferred")

    def __init__(self, domains: Set[int], preferred: bool):
        self.domains = frozenset(domains)
        self.preferred = preferred

    def __repr__(self):  # pragma: no cover
        return f"Hint({sorted(self.domains)}, preferred={self.preferred})"


class DeviceManager:
    """Kubelet-side manager: registry server + allocation bookkeeping.

    policy: 'best-effort' prefers single-domain allocations but proceeds
    unaligned; 'restricted' fails admission when alignment is impossible
    (topologymanager policies of the same names)."""

    def __init__(
        self,
        socket_path: str,
        checkpoint_path: Optional[str] = None,
        policy: str = "best-effort",
    ):
        if policy not in ("best-effort", "restricted"):
            raise ValueError(f"unknown topology policy {policy!r}")
        self.socket_path = socket_path
        self.checkpoint_path = checkpoint_path
        self.policy = policy
        self._lock = threading.Lock()
        self._endpoints: Dict[str, _Endpoint] = {}  # resource -> endpoint
        # pod key -> resource -> [device ids]
        self._allocations: Dict[str, Dict[str, List[str]]] = {}
        self._srv: Optional[socketserver.ThreadingUnixStreamServer] = None
        self._generation = 0  # bumped on capacity-visible changes
        self._load_checkpoint()

    # -- registry server (kubelet.sock) --------------------------------------

    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        method, payload = _recv_frame(self.request)
                        try:
                            resp = outer._dispatch(method, payload)
                            _reply(self.request, 0, resp)
                        except Exception as e:
                            _reply(self.request, 1, {"error": str(e)})
                except (ConnectionError, OSError):
                    pass

        self._srv = socketserver.ThreadingUnixStreamServer(
            self.socket_path, Handler
        )
        self._srv.daemon_threads = True
        threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="deviceplugin-registry"
        ).start()

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def _dispatch(self, method: str, payload: dict) -> dict:
        if method in ("Register", "Update"):
            devices = {
                d["id"]: Device(
                    d["id"], d.get("healthy", True), int(d.get("topology", 0))
                )
                for d in payload.get("devices", [])
            }
            with self._lock:
                ep = self._endpoints.get(payload["resource"])
                if ep is None or method == "Register":
                    ep = _Endpoint(
                        payload["resource"], payload.get("endpoint", "")
                    )
                    self._endpoints[payload["resource"]] = ep
                ep.devices = devices
                self._generation += 1
            logger.info(
                "device plugin %s: %s with %d devices",
                payload["resource"],
                method.lower(),
                len(devices),
            )
            return {}
        raise ValueError(f"unimplemented device-plugin method {method!r}")

    # -- capacity surface ----------------------------------------------------

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def capacities(self) -> Dict[str, int]:
        """resource -> healthy device count (merged into Node allocatable by
        the kubelet's status sync; NodeResourcesFit — host and kernel —
        then schedules against them as extended resources)."""
        with self._lock:
            return {
                res: sum(1 for d in ep.devices.values() if d.healthy)
                for res, ep in self._endpoints.items()
            }

    def _in_use(self, resource: str) -> Set[str]:
        used: Set[str] = set()
        for per_pod in self._allocations.values():
            used.update(per_pod.get(resource, ()))
        return used

    # -- topology hints (topology_hints.go) ----------------------------------

    def topology_hints(self, resource: str, count: int) -> List[TopologyHint]:
        """Possible locality-domain sets that can satisfy `count` devices
        of `resource`; single-domain sets are preferred."""
        with self._lock:
            return self._topology_hints_locked(resource, count)

    def _topology_hints_locked(
        self, resource: str, count: int
    ) -> List[TopologyHint]:
        ep = self._endpoints.get(resource)
        if ep is None:
            return []
        used = self._in_use(resource)
        by_domain: Dict[int, int] = {}
        for d in ep.devices.values():
            if d.healthy and d.id not in used:
                by_domain[d.topology] = by_domain.get(d.topology, 0) + 1
        hints = [
            TopologyHint({dom}, True)
            for dom, avail in by_domain.items()
            if avail >= count
        ]
        if sum(by_domain.values()) >= count:
            # the cross-domain (unaligned) fallback hint
            hints.append(TopologyHint(set(by_domain), len(by_domain) <= 1))
        return hints

    def _merge_hints(
        self, per_resource: Dict[str, List[TopologyHint]]
    ) -> Optional[TopologyHint]:
        """Best single merged hint: every resource must be satisfiable
        within the merged domain set; prefer (preferred, fewest domains).
        None = some resource cannot be satisfied at all."""
        merged: Optional[TopologyHint] = None
        import itertools

        for combo in itertools.product(*per_resource.values()):
            domains = frozenset().union(*(h.domains for h in combo))
            preferred = all(h.preferred for h in combo) and len(domains) <= 1
            cand = TopologyHint(set(domains), preferred)
            if merged is None or (cand.preferred, -len(cand.domains)) > (
                merged.preferred,
                -len(merged.domains),
            ):
                merged = cand
        return merged

    # -- allocation (Allocate + checkpoint) ----------------------------------

    def allocate_pod(self, pod) -> Dict[str, List[str]]:
        """Admission-time allocation for every plugin resource the pod's
        containers request. Returns {resource: [device ids]}; raises when
        the request cannot be satisfied (or, under the 'restricted'
        policy, cannot be topology-aligned). Idempotent per pod key."""
        key = pod.metadata.key
        wants: Dict[str, int] = {}
        for c in pod.spec.containers:
            for name, qty in c.requests.items():
                if name in self._endpoints:
                    wants[name] = wants.get(name, 0) + int(str(qty))
        if not wants:
            return {}
        # hints + merge + grant under ONE lock hold: computing the hints
        # lock-free and re-locking for the grant lets a concurrent
        # allocation consume the aligned pool in between, silently
        # spilling cross-domain even under policy='restricted' (the
        # alignment guarantee the merged.preferred check enforces)
        granted: Dict[str, List[str]] = {}
        with self._lock:
            if key in self._allocations:
                return dict(self._allocations[key])
            hints = {
                res: self._topology_hints_locked(res, cnt)
                for res, cnt in wants.items()
            }
            for res, hs in hints.items():
                if not hs:
                    raise RuntimeError(
                        f"insufficient {res}: want {wants[res]}, none available"
                    )
            merged = self._merge_hints(hints)
            if merged is None:
                raise RuntimeError(f"cannot satisfy device request {wants}")
            if self.policy == "restricted" and not merged.preferred:
                raise RuntimeError(
                    f"topology policy=restricted: no aligned allocation for "
                    f"{wants}"
                )
            for res, cnt in wants.items():
                ep = self._endpoints[res]
                used = self._in_use(res)
                pool = [
                    d
                    for d in ep.devices.values()
                    if d.healthy and d.id not in used
                ]
                # aligned devices first, then spill (best-effort)
                pool.sort(key=lambda d: (d.topology not in merged.domains, d.id))
                if len(pool) < cnt:
                    raise RuntimeError(
                        f"insufficient {res}: want {cnt}, have {len(pool)}"
                    )
                grant = pool[:cnt]
                if self.policy == "restricted" and any(
                    d.topology not in merged.domains for d in grant
                ):
                    # belt-and-braces: the hint said aligned capacity
                    # exists; a grant outside merged.domains would violate
                    # the restricted contract — fail admission instead
                    raise RuntimeError(
                        f"topology policy=restricted: aligned pool for {res} "
                        "exhausted during allocation"
                    )
                granted[res] = [d.id for d in grant]
            self._allocations[key] = granted
            self._save_checkpoint_locked()
        # dial each plugin's endpoint for the actual Allocate call (the
        # reference's back-connection to the plugin's gRPC server)
        for res, ids in granted.items():
            ep = self._endpoints[res]
            if ep.endpoint:
                try:
                    self._call_plugin(ep.endpoint, "Allocate", {"device_ids": ids})
                except Exception:
                    with self._lock:
                        self._allocations.pop(key, None)
                        self._save_checkpoint_locked()
                    raise
        return granted

    def free_pod(self, pod_key: str) -> None:
        with self._lock:
            if self._allocations.pop(pod_key, None) is not None:
                self._save_checkpoint_locked()

    def allocations(self, pod_key: str) -> Dict[str, List[str]]:
        with self._lock:
            return dict(self._allocations.get(pod_key, {}))

    @staticmethod
    def _call_plugin(endpoint: str, method: str, payload: dict) -> dict:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(10.0)
        try:
            s.connect(endpoint)
            _send_frame(s, method, payload)
            return _read_reply(s)
        finally:
            s.close()

    # -- checkpoint (checkpoint/checkpoint.go) --------------------------------

    def _save_checkpoint_locked(self) -> None:
        if self.checkpoint_path is None:
            return
        tmp = self.checkpoint_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"allocations": self._allocations}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.checkpoint_path)

    def _load_checkpoint(self) -> None:
        if self.checkpoint_path is None or not os.path.exists(
            self.checkpoint_path
        ):
            return
        try:
            with open(self.checkpoint_path, encoding="utf-8") as f:
                self._allocations = json.load(f).get("allocations", {})
        except (json.JSONDecodeError, OSError):
            logger.exception("device checkpoint unreadable; starting empty")
            self._allocations = {}


class DevicePluginStub:
    """Plugin-side helper: registers with the kubelet and serves Allocate
    on its own endpoint socket (the e2e device plugin's shape,
    test/e2e_node/testdeviceplugin). Real plugins (a TPU chip plugin) use
    the same wire contract from their own process."""

    def __init__(
        self,
        kubelet_socket: str,
        resource: str,
        devices: List[Device],
        endpoint: Optional[str] = None,
    ):
        self.kubelet_socket = kubelet_socket
        self.resource = resource
        self.devices = list(devices)
        self.endpoint = endpoint or f"{kubelet_socket}.{resource.replace('/', '_')}"
        self.allocated: List[List[str]] = []  # observed Allocate calls
        self._reg: Optional[socket.socket] = None
        self._srv: Optional[socketserver.ThreadingUnixStreamServer] = None

    def start(self) -> None:
        outer = self
        if os.path.exists(self.endpoint):
            os.unlink(self.endpoint)

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        method, payload = _recv_frame(self.request)
                        if method == "Allocate":
                            outer.allocated.append(payload["device_ids"])
                            _reply(self.request, 0, {"envs": {}})
                        else:
                            _reply(
                                self.request, 1, {"error": f"bad method {method}"}
                            )
                except (ConnectionError, OSError):
                    pass

        self._srv = socketserver.ThreadingUnixStreamServer(self.endpoint, Handler)
        self._srv.daemon_threads = True
        threading.Thread(
            target=self._srv.serve_forever, daemon=True, name="deviceplugin-stub"
        ).start()
        self._reg = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._reg.settimeout(10.0)
        self._reg.connect(self.kubelet_socket)
        self._send_devices("Register")

    def _send_devices(self, method: str) -> None:
        _send_frame(
            self._reg,
            method,
            {
                "resource": self.resource,
                "endpoint": self.endpoint,
                "devices": [
                    {"id": d.id, "healthy": d.healthy, "topology": d.topology}
                    for d in self.devices
                ],
            },
        )
        _read_reply(self._reg)

    def update_devices(self, devices: List[Device]) -> None:
        """ListAndWatch push: health/topology changes stream to the manager."""
        self.devices = list(devices)
        self._send_devices("Update")

    def stop(self) -> None:
        if self._reg is not None:
            self._reg.close()
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
        if os.path.exists(self.endpoint):
            os.unlink(self.endpoint)
