"""CSI node-driver boundary: the kubelet side of external CSI drivers.

Reference: pkg/volume/csi/csi_client.go — the kubelet dials a driver's
unix socket and drives the CSI node service around pod volume setup:
NodeStageVolume (device mount, once per node) -> NodePublishVolume (per
pod) and the inverse NodeUnpublishVolume -> NodeUnstageVolume. Driver
discovery mirrors the plugin-registration flow
(pkg/kubelet/pluginmanager): a driver announces {name, endpoint} and the
kubelet remembers the socket.

Transport is the same framed unix-socket mini-RPC the device-plugin
manager speaks (kubelet/devicemanager.py) — this build's stand-in for
CSI's gRPC, crossing a real process boundary with the real call
sequence. A driver that is not registered leaves the volume pending
(reconcile retries), exactly like a missing CSI plugin in the
reference.
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Dict, List, Optional, Set, Tuple

from .devicemanager import _read_reply, _send_frame

logger = logging.getLogger("kubernetes_tpu.kubelet.csi")


class CSIError(RuntimeError):
    pass


class CSIDriverManager:
    """Registered CSI node drivers + the four node-service calls."""

    def __init__(self, node_name: str = ""):
        self.node_name = node_name
        self._lock = threading.Lock()
        self._drivers: Dict[str, str] = {}  # driver name -> unix socket
        # staged volume handles per driver (NodeStage is once-per-node;
        # publish fans out per pod)
        self._staged: Set[Tuple[str, str]] = set()

    # -- registration (pluginmanager handshake) ------------------------------

    def register(self, driver: str, endpoint: str) -> None:
        with self._lock:
            self._drivers[driver] = endpoint
        logger.info("csi driver %s registered at %s", driver, endpoint)

    def unregister(self, driver: str) -> None:
        with self._lock:
            self._drivers.pop(driver, None)

    def has_driver(self, driver: str) -> bool:
        with self._lock:
            return driver in self._drivers

    def drivers(self) -> List[str]:
        with self._lock:
            return sorted(self._drivers)

    # -- node service --------------------------------------------------------

    def _call(self, driver: str, method: str, payload: dict) -> dict:
        with self._lock:
            endpoint = self._drivers.get(driver)
        if endpoint is None:
            raise CSIError(f"csi driver {driver!r} is not registered")
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(10.0)
        try:
            s.connect(endpoint)
            _send_frame(s, method, payload)
            return _read_reply(s)
        except OSError as e:
            raise CSIError(f"csi {driver} {method}: {e}") from e
        finally:
            s.close()

    def stage_and_publish(self, csi_source, pod_key: str) -> None:
        """MountDevice + SetUp for one (pod, csi volume): NodeStageVolume
        on the volume's first use on this node, then NodePublishVolume
        for the pod. Raises CSIError to leave the volume pending."""
        key = (csi_source.driver, csi_source.volume_handle)
        with self._lock:
            staged = key in self._staged
        if not staged:
            self._call(
                csi_source.driver,
                "NodeStageVolume",
                {
                    "volume_id": csi_source.volume_handle,
                    "node": self.node_name,
                },
            )
            with self._lock:
                self._staged.add(key)
        self._call(
            csi_source.driver,
            "NodePublishVolume",
            {
                "volume_id": csi_source.volume_handle,
                "target": pod_key,
                "readonly": bool(csi_source.read_only),
            },
        )

    def unpublish(self, csi_source, pod_key: str, last_user: bool) -> bool:
        """TearDown (+ UnmountDevice when the last pod leaves):
        NodeUnpublishVolume, then NodeUnstageVolume. Returns False on a
        driver fault so the CALLER keeps the pair mounted and the next
        reconcile pass retries — a dead driver must not wedge pod
        deletion, but it must not leak the driver-side publish either."""
        try:
            self._call(
                csi_source.driver,
                "NodeUnpublishVolume",
                {"volume_id": csi_source.volume_handle, "target": pod_key},
            )
            if last_user:
                self._call(
                    csi_source.driver,
                    "NodeUnstageVolume",
                    {"volume_id": csi_source.volume_handle},
                )
                with self._lock:
                    self._staged.discard(
                        (csi_source.driver, csi_source.volume_handle)
                    )
        except CSIError as e:
            logger.warning("csi teardown (retried next pass): %s", e)
            return False
        return True

    def staged(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(self._staged)
