"""Container manager: node allocatable + pod cgroup layout.

Reference: pkg/kubelet/cm/container_manager_linux.go (Node Allocatable
enforcement: allocatable = capacity - kube-reserved - system-reserved -
eviction threshold) and cm/pod_container_manager_linux.go (the
/kubepods/{qos}/pod{uid} cgroup tree). There are no real cgroups to write
here (the hollow runtime), but the ACCOUNTING is real: the allocatable the
scheduler packs against is capacity minus reservations, and every pod has
a deterministic cgroup path derived from its QoS class — the same numbers
and layout a real node would enforce.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..api import objects as v1
from ..api.resources import cpu_to_millis, parse_quantity
from .eviction import QOS_BEST_EFFORT, QOS_BURSTABLE, QOS_GUARANTEED, qos_class

_QOS_CGROUP = {
    QOS_GUARANTEED: "",  # guaranteed pods sit directly under kubepods
    QOS_BURSTABLE: "burstable",
    QOS_BEST_EFFORT: "besteffort",
}


class ContainerManager:
    def __init__(
        self,
        system_reserved: Optional[Dict[str, str]] = None,
        kube_reserved: Optional[Dict[str, str]] = None,
        eviction_hard_memory: str = "0",
    ):
        self.system_reserved = dict(system_reserved or {})
        self.kube_reserved = dict(kube_reserved or {})
        self.eviction_hard_memory = eviction_hard_memory

    def node_allocatable(self, capacity: Dict[str, object]) -> Dict[str, object]:
        """Allocatable = capacity - reservations (GetNodeAllocatableReservation):
        cpu in millicores, memory in bytes (memory also subtracts the hard
        eviction threshold, matching the reference's formula). Unreserved
        resources pass through unchanged."""
        out: Dict[str, object] = dict(capacity)
        cpu_res = sum(
            cpu_to_millis(r.get("cpu", 0))
            for r in (self.system_reserved, self.kube_reserved)
        )
        if "cpu" in capacity and cpu_res:
            out["cpu"] = f"{max(cpu_to_millis(capacity['cpu']) - cpu_res, 0)}m"
        mem_res = sum(
            int(parse_quantity(r.get("memory", 0)))
            for r in (self.system_reserved, self.kube_reserved)
        ) + int(parse_quantity(self.eviction_hard_memory))
        if "memory" in capacity and mem_res:
            # quantity STRING like every other allocatable in the system
            # (plain byte count is a valid k8s quantity)
            out["memory"] = str(
                max(int(parse_quantity(capacity["memory"])) - mem_res, 0)
            )
        return out

    @staticmethod
    def pod_cgroup(pod: v1.Pod) -> str:
        """/kubepods[/{qos}]/pod{uid} (pod_container_manager_linux.go
        GetPodContainerName)."""
        qos = _QOS_CGROUP[qos_class(pod)]
        parts = ["kubepods"]
        if qos:
            parts.append(qos)
        # key fallback sanitized: "ns/name" must stay ONE path segment
        ident = pod.metadata.uid or pod.metadata.key.replace("/", "_")
        parts.append(f"pod{ident}")
        return "/" + "/".join(parts)
