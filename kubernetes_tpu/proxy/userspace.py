"""Userspace proxy mode: real TCP listeners + byte splicing.

Reference: pkg/proxy/userspace/proxier.go — the oldest kube-proxy mode
opens a REAL listening socket per service port, accepts connections in
userspace, dials a backend chosen by the load balancer, and copies bytes
both ways. This build does exactly that: one 127.0.0.1 listener per
(service, port), backend selection through the Proxier's resolve table
(round-robin / session affinity), bidirectional splice threads.

Divergence: the reference binds a random proxy port and installs
iptables redirects from the clusterIP; with no iptables here, clients
dial the proxy port directly (``proxy_port()``). Backends must be
reachable addresses (e.g. 127.0.0.1 endpoints) — pods on the simulated
network can't be spliced to, same as any unreachable endpoint."""

from __future__ import annotations

import logging
import socket
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.proxy.userspace")

_BUF = 65536


def _splice(a: socket.socket, b: socket.socket) -> None:
    """Copy a→b until EOF/error, then signal write-shutdown downstream."""
    try:
        while True:
            data = a.recv(_BUF)
            if not data:
                break
            b.sendall(data)
    except OSError:
        pass
    finally:
        try:
            b.shutdown(socket.SHUT_WR)
        except OSError:
            pass


class SpliceListener:
    """One real listening socket for one (service vip, port)."""

    def __init__(self, proxier, vip: str, port: int):
        self.proxier = proxier
        self.vip = vip
        self.port = port
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(64)
        self.proxy_port = self._sock.getsockname()[1]
        self._closed = False
        self._t = threading.Thread(
            target=self._accept_loop,
            daemon=True,
            name=f"userspace-{vip}:{port}",
        )
        self._t.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn, peer), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, peer) -> None:
        backend = self.proxier.resolve(
            self.vip, self.port, client_key=str(peer[0])
        )
        if backend is None:
            conn.close()  # no endpoints: connection refused semantics
            return
        host, bport = backend
        up = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            up.settimeout(10.0)
            up.connect((host, int(bport)))
            up.settimeout(None)
        except OSError:
            conn.close()
            up.close()
            self.proxier.release(backend)
            return
        t = threading.Thread(target=_splice, args=(up, conn), daemon=True)
        t.start()
        _splice(conn, up)
        t.join()
        conn.close()
        up.close()
        self.proxier.release(backend)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class UserspaceManager:
    """Reconciles listeners against the proxier's synced table: one
    listener per (service-key vip, numeric port); services/ports that
    vanish get their listener closed."""

    def __init__(self, proxier):
        self.proxier = proxier
        self._lock = threading.Lock()
        self._listeners: Dict[Tuple[str, int], SpliceListener] = {}

    def reconcile(self, table_keys) -> None:
        want = {
            (vip, port)
            for vip, port in table_keys
            if "/" in vip and isinstance(port, int)
        }
        with self._lock:
            for key in list(self._listeners):
                if key not in want:
                    self._listeners.pop(key).close()
            for vip, port in want:
                if (vip, port) not in self._listeners:
                    try:
                        self._listeners[(vip, port)] = SpliceListener(
                            self.proxier, vip, port
                        )
                    except OSError as e:
                        logger.warning(
                            "userspace listen %s:%s: %s", vip, port, e
                        )

    def proxy_port(self, svc_key: str, port: int) -> Optional[int]:
        with self._lock:
            ln = self._listeners.get((svc_key, port))
            return ln.proxy_port if ln else None

    def close(self) -> None:
        with self._lock:
            for ln in self._listeners.values():
                ln.close()
            self._listeners.clear()
