"""kube-proxy-lite: the per-node service VIP dataplane.

Reference shape: pkg/proxy/iptables/proxier.go — the proxier watches
Services + Endpoints, and `syncProxyRules` (proxier.go:775) rebuilds the
node's full NAT table on every sync: one chain per service port
(KUBE-SVC-*), one per endpoint (KUBE-SEP-*) with statistical round-robin,
and ClientIP session affinity via `recent` match. Changes are accumulated
in change-tracker maps and applied atomically by iptables-restore.

This build has no netfilter to program; the dataplane is a process-local
routing table the (hollow) pod runtime queries to reach a VIP:

    table: (cluster_ip | "ns/name", port_name_or_number) -> [backends]
    resolve(vip, port, client_key) -> one backend (RR or ClientIP-hash)

The sync loop mirrors syncProxyRules' structure: event handlers only mark
pending changes; a single sync rebuilds the whole table from the informer
caches and swaps it atomically (readers never see a partial table); a
min-sync interval coalesces event bursts the way the proxier's
BoundedFrequencyRunner does.
"""

from __future__ import annotations

import itertools
import logging
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..api import objects as v1
from ..client.informers import SharedInformerFactory

logger = logging.getLogger("kubernetes_tpu.proxy")

AFFINITY_ANNOTATION = "service.kubernetes.io/session-affinity"  # "ClientIP"


class Proxier:
    """One per node (NodeAgentPool shares one per process — the table is
    node-independent in this build since there is no real network)."""

    def __init__(
        self,
        server,
        node_name: str = "",
        min_sync_period: float = 0.05,
        informer_factory: Optional[SharedInformerFactory] = None,
    ):
        self.server = server
        self.node_name = node_name
        self.min_sync = min_sync_period
        self._own_informers = informer_factory is None
        self.informers = informer_factory or SharedInformerFactory(server)
        self._lock = threading.Lock()
        self._table: Dict[Tuple[str, object], List[Tuple[str, int]]] = {}
        self._affinity: Dict[str, str] = {}  # every vip key -> affinity mode
        self._rr: Dict[Tuple[str, object], int] = {}  # per-(vip, port) RR
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.syncs = 0  # sync counter (tests/metrics)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        svc_inf = self.informers.informer("services")
        ep_inf = self.informers.informer("endpoints")
        mark = lambda *_a, **_k: self._dirty.set()  # noqa: E731
        svc_inf.add_handler(on_add=mark, on_update=mark, on_delete=mark)
        ep_inf.add_handler(on_add=mark, on_update=mark, on_delete=mark)
        if self._own_informers:
            self.informers.start()
            self.informers.wait_for_cache_sync()
        self._dirty.set()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"proxier-{self.node_name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        if self._own_informers:
            self.informers.stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait()
            if self._stop.is_set():
                return
            self._dirty.clear()
            try:
                self.sync_proxy_rules()
            except Exception:
                logger.exception("proxy sync failed")
            # BoundedFrequencyRunner: coalesce event bursts
            self._stop.wait(self.min_sync)

    # -- the sync (syncProxyRules-shaped: full rebuild, atomic swap) --------

    def sync_proxy_rules(self) -> None:
        services, _ = self.server.list("services")
        table: Dict[Tuple[str, object], List[Tuple[str, int]]] = {}
        affinity: Dict[str, str] = {}
        for svc in services:
            mode = svc.metadata.annotations.get(AFFINITY_ANNOTATION, "")
            try:
                eps = self.server.get(
                    "endpoints", svc.metadata.namespace, svc.metadata.name
                )
            except Exception:
                eps = None
            backends_by_port: Dict[object, List[Tuple[str, int]]] = {}
            if eps is not None:
                for subset in eps.subsets:
                    for pname, pnum in subset.ports or [("", 0)]:
                        # route by number AND name: kube-proxy keys rules by
                        # service port number; names are aliases
                        lst: List[Tuple[str, int]] = []
                        for addr in subset.addresses:
                            lst.append((addr.ip or addr.target_pod, pnum))
                        for port_id in {pname, pnum} - {""}:
                            backends_by_port.setdefault(port_id, []).extend(lst)
            for vip_key in self._vips(svc):
                affinity[vip_key] = mode
                for port_id, backends in backends_by_port.items():
                    table[(vip_key, port_id)] = backends
                if not backends_by_port:
                    # service with no endpoints: present but empty (the
                    # proxier emits a REJECT rule; resolve returns None)
                    table[(vip_key, None)] = []
        with self._lock:
            self._table = table
            self._affinity = affinity
            self.syncs += 1

    @staticmethod
    def _vips(svc: v1.Service) -> List[str]:
        vips = [svc.metadata.key]  # "ns/name" — DNS-ish lookup
        if svc.spec.cluster_ip:
            vips.append(svc.spec.cluster_ip)
        return vips

    # -- the query plane ----------------------------------------------------

    def resolve(
        self, vip: str, port: object = None, client_key: str = ""
    ) -> Optional[Tuple[str, int]]:
        """One backend for vip:port — round-robin, or a stable ClientIP hash
        when the service requests session affinity (proxier.go `recent`
        match equivalent)."""
        with self._lock:
            backends = self._table.get((vip, port))
            if backends is None and port is None:
                # unique port fallback: a service with one port resolves
                # without naming it
                cands = [
                    v
                    for (k, p), v in self._table.items()
                    if k == vip and p is not None
                ]
                backends = cands[0] if len(cands) == 1 else None
            if not backends:
                return None
            if self._affinity.get(vip, "") == "ClientIP" and client_key:
                i = zlib.crc32(client_key.encode()) % len(backends)
            else:
                n = self._rr.get((vip, port), 0)
                self._rr[(vip, port)] = n + 1
                i = n % len(backends)
            return backends[i]

    def endpoints_of(self, vip: str, port: object = None) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._table.get((vip, port), []))

    def wait_synced(self, timeout: float = 5.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.syncs > 0 and not self._dirty.is_set():
                    return True
            time.sleep(0.01)
        return False


class ClusterIPAllocator:
    """Admit hook: assigns a virtual ClusterIP from a /16 at Service create —
    the in-process stand-in for the apiserver's service IP allocator
    (reference pkg/registry/core/service ipallocator)."""

    def __init__(self, prefix: str = "10.96"):
        self.prefix = prefix
        self._next = itertools.count(1)
        self._lock = threading.Lock()

    def __call__(self, verb: str, kind: str, obj) -> None:
        if verb != "create" or kind != "services":
            return
        if getattr(obj.spec, "cluster_ip", ""):
            return
        with self._lock:
            n = next(self._next)
        obj.spec.cluster_ip = f"{self.prefix}.{(n >> 8) & 0xFF}.{n & 0xFF}"
