"""kube-proxy-lite: the per-node service VIP dataplane.

Reference shape: pkg/proxy/iptables/proxier.go — the proxier watches
Services + EndpointSlices, change trackers accumulate deltas, and
`syncProxyRules` (proxier.go:775) rebuilds NAT chains applied atomically by
iptables-restore. This build has no netfilter to program; the dataplane is
a process-local routing table the (hollow) pod runtime queries to reach a
VIP:

    table: (cluster_ip | "ns/name", port_name_or_number) -> [backends]
    resolve(vip, port, client_key) -> one backend

Parity points:
  * **EndpointSlice-driven** (pkg/proxy/endpointslicecache.go): backends
    come from discovery slices (`kubernetes.io/service-name` label, ready
    endpoints only), merged across a service's slices; the legacy
    Endpoints object is the fallback for services with no slices — the
    same dual-source arrangement as the EndpointSliceProxying gate era.
  * **Change tracking**: event handlers record which SERVICES changed
    (service events directly, slice events via their service label); the
    sync recomputes only those services unless a full rebuild is due —
    the ServiceChangeTracker/EndpointChangeTracker split.
  * **Three modes**: "iptables" resolves statistically (round-robin, the
    `--mode random` chain equivalent); "ipvs" adds real virtual-server
    scheduling — least-connection with live connection tracking
    (pkg/proxy/ipvs/proxier.go's rr/lc schedulers); "userspace" runs
    REAL TCP listeners with byte splicing to live backends
    (proxy/userspace.py, pkg/proxy/userspace/proxier.go). The reference's
    fourth mode, winkernel, is deliberately out of scope: it drives the
    Windows HNS dataplane and this build targets Linux only.
  * ClientIP session affinity via a stable hash in every mode.

A min-sync interval coalesces event bursts the way the proxier's
BoundedFrequencyRunner does.
"""

from __future__ import annotations

import itertools
import logging
import threading
import zlib
from typing import Dict, List, Optional, Set, Tuple

from ..api import objects as v1
from ..client.informers import SharedInformerFactory

logger = logging.getLogger("kubernetes_tpu.proxy")

AFFINITY_ANNOTATION = "service.kubernetes.io/session-affinity"  # "ClientIP"
SERVICE_NAME_LABEL = "kubernetes.io/service-name"


class EndpointSliceCache:
    """Applied-slice state per service (pkg/proxy/endpointslicecache.go):
    slices keyed by (namespace, slice name); backends_for merges a
    service's slices into per-port backend lists (ready endpoints only)."""

    def __init__(self):
        self._slices: Dict[Tuple[str, str], v1.EndpointSlice] = {}

    @staticmethod
    def _svc_key(es: v1.EndpointSlice) -> Optional[str]:
        svc = es.metadata.labels.get(SERVICE_NAME_LABEL)
        return f"{es.metadata.namespace}/{svc}" if svc else None

    def update(self, es: v1.EndpointSlice) -> set:
        """Apply one slice; returns every service key affected — including
        the PREVIOUS owner when the service-name label changed or vanished
        (its table rows would otherwise serve the removed endpoints
        forever)."""
        slot = (es.metadata.namespace, es.metadata.name)
        old = self._slices.pop(slot, None)
        keys = set()
        if old is not None:
            old_key = self._svc_key(old)
            if old_key:
                keys.add(old_key)
        new_key = self._svc_key(es)
        if new_key:
            self._slices[slot] = es
            keys.add(new_key)
        return keys

    def remove(self, es: v1.EndpointSlice) -> set:
        old = self._slices.pop((es.metadata.namespace, es.metadata.name), None)
        key = self._svc_key(old if old is not None else es)
        return {key} if key else set()

    def has_slices(self, svc_key: str) -> bool:
        ns, _, name = svc_key.partition("/")
        return any(
            k[0] == ns and s.metadata.labels.get(SERVICE_NAME_LABEL) == name
            for k, s in self._slices.items()
        )

    def backends_for(self, svc_key: str) -> Dict[object, List[Tuple[str, int]]]:
        ns, _, name = svc_key.partition("/")
        out: Dict[object, List[Tuple[str, int]]] = {}
        for (sns, _sname), es in sorted(self._slices.items()):
            if sns != ns or es.metadata.labels.get(SERVICE_NAME_LABEL) != name:
                continue
            for pname, pnum in es.ports or [("", 0)]:
                lst: List[Tuple[str, int]] = []
                for ep in es.endpoints:
                    if not ep.ready:
                        continue  # unready endpoints are not routed
                    addr = (ep.addresses[0] if ep.addresses else "") or ep.target_pod
                    if addr:
                        lst.append((addr, pnum))
                for port_id in {pname, pnum} - {""}:
                    out.setdefault(port_id, []).extend(lst)
        return out


class Proxier:
    """One per node (NodeAgentPool shares one per process — the table is
    node-independent in this build since there is no real network).

    mode: "iptables" (statistical round-robin) or "ipvs" (virtual-server
    scheduling; scheduler "rr" or "lc" least-connection)."""

    def __init__(
        self,
        server,
        node_name: str = "",
        min_sync_period: float = 0.05,
        informer_factory: Optional[SharedInformerFactory] = None,
        mode: str = "iptables",
        ipvs_scheduler: str = "lc",
    ):
        if mode not in ("iptables", "ipvs", "userspace"):
            raise ValueError(f"unknown proxy mode {mode!r}")
        if ipvs_scheduler not in ("rr", "lc"):
            raise ValueError(f"unknown ipvs scheduler {ipvs_scheduler!r}")
        self.server = server
        self.node_name = node_name
        self.mode = mode
        self.ipvs_scheduler = ipvs_scheduler
        self.min_sync = min_sync_period
        self._own_informers = informer_factory is None
        self.informers = informer_factory or SharedInformerFactory(server)
        self._lock = threading.Lock()
        self._table: Dict[Tuple[str, object], List[Tuple[str, int]]] = {}
        self._affinity: Dict[str, str] = {}  # every vip key -> affinity mode
        self._rr: Dict[Tuple[str, object], int] = {}  # per-(vip, port) RR
        self._conns: Dict[Tuple[str, int], int] = {}  # ipvs lc: active conns
        self._slice_cache = EndpointSliceCache()
        # change trackers: service keys needing recompute; None entry = full
        self._pending: Set[str] = set()
        self._full = True
        # vip -> service key (so per-service recompute can drop stale vips)
        self._vips_of: Dict[str, List[str]] = {}
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.syncs = 0  # sync counter (tests/metrics)
        self.slice_routed = 0  # services routed via EndpointSlices (tests)
        self.legacy_routed = 0  # services routed via the Endpoints fallback
        # userspace mode: real TCP listeners + splicing (proxy/userspace.py)
        self.userspace = None
        if mode == "userspace":
            from .userspace import UserspaceManager

            self.userspace = UserspaceManager(self)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        svc_inf = self.informers.informer("services")
        eps_inf = self.informers.informer("endpointslices")
        ep_inf = self.informers.informer("endpoints")

        def svc_changed(*objs):
            with self._lock:
                for o in objs:
                    if o is not None:
                        self._pending.add(o.metadata.key)
            self._dirty.set()

        def slice_changed(remove, *objs):
            with self._lock:
                for o in objs:
                    if o is None:
                        continue
                    keys = (
                        self._slice_cache.remove(o)
                        if remove
                        else self._slice_cache.update(o)
                    )
                    self._pending.update(keys)
            self._dirty.set()

        def ep_changed(*objs):
            # legacy Endpoints: only matters for services with no slices
            with self._lock:
                for o in objs:
                    if o is not None:
                        self._pending.add(o.metadata.key)
            self._dirty.set()

        svc_inf.add_handler(
            on_add=lambda s: svc_changed(s),
            on_update=lambda o, n: svc_changed(o, n),
            on_delete=lambda s: svc_changed(s),
        )
        eps_inf.add_handler(
            on_add=lambda s: slice_changed(False, s),
            on_update=lambda o, n: slice_changed(False, n),
            on_delete=lambda s: slice_changed(True, s),
        )
        ep_inf.add_handler(
            on_add=lambda e: ep_changed(e),
            on_update=lambda o, n: ep_changed(n),
            on_delete=lambda e: ep_changed(e),
        )
        if self._own_informers:
            self.informers.start()
            self.informers.wait_for_cache_sync()
        with self._lock:
            self._full = True
        self._dirty.set()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"proxier-{self.node_name}"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._dirty.set()
        if self.userspace is not None:
            self.userspace.close()
        if self._own_informers:
            self.informers.stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._dirty.wait()
            if self._stop.is_set():
                return
            self._dirty.clear()
            try:
                self.sync_proxy_rules()
            except Exception:
                logger.exception("proxy sync failed")
            # BoundedFrequencyRunner: coalesce event bursts
            self._stop.wait(self.min_sync)

    # -- the sync (syncProxyRules-shaped; change-tracked) --------------------

    def sync_proxy_rules(self) -> None:
        with self._lock:
            full, self._full = self._full, False
            pending, self._pending = self._pending, set()
        services, _ = self.server.list("services")
        by_key = {s.metadata.key: s for s in services}
        targets = by_key if full else {
            k: by_key.get(k) for k in pending
        }
        new_entries: Dict[str, Dict[Tuple[str, object], List]] = {}
        new_affinity: Dict[str, str] = {}
        new_vips: Dict[str, List[str]] = {}
        for key, svc in targets.items():
            if svc is None:
                new_vips[key] = []  # deleted service: drop its vips
                continue
            backends_by_port = self._backends_for(svc)
            vips = self._vips(svc)
            new_vips[key] = vips
            entries: Dict[Tuple[str, object], List] = {}
            mode = svc.metadata.annotations.get(AFFINITY_ANNOTATION, "")
            for vip_key in vips:
                new_affinity[vip_key] = mode
                for port_id, backends in backends_by_port.items():
                    entries[(vip_key, port_id)] = backends
                if not backends_by_port:
                    # service with no endpoints: present but empty (the
                    # proxier emits a REJECT rule; resolve returns None)
                    entries[(vip_key, None)] = []
            new_entries[key] = entries
        with self._lock:
            if full:
                self._table = {}
                self._affinity = {}
                self._vips_of = {}
            for key in new_vips:
                # drop the service's previous vip rows, then re-add
                for vip in self._vips_of.get(key, ()):
                    self._affinity.pop(vip, None)
                    for tk in [t for t in self._table if t[0] == vip]:
                        del self._table[tk]
                self._vips_of[key] = new_vips[key]
            for key, entries in new_entries.items():
                self._table.update(entries)
            self._affinity.update(new_affinity)
            self.syncs += 1
            table_keys = list(self._table) if self.userspace else ()
        if self.userspace is not None:
            self.userspace.reconcile(table_keys)

    def _backends_for(self, svc: v1.Service) -> Dict[object, List[Tuple[str, int]]]:
        """EndpointSlices first; the legacy Endpoints object only for
        services with no slices at all (the dual-source fallback)."""
        key = svc.metadata.key
        with self._lock:
            has_slices = self._slice_cache.has_slices(key)
            if has_slices:
                self.slice_routed += 1
                return self._slice_cache.backends_for(key)
        try:
            eps = self.server.get(
                "endpoints", svc.metadata.namespace, svc.metadata.name
            )
        except Exception:
            return {}
        backends_by_port: Dict[object, List[Tuple[str, int]]] = {}
        for subset in eps.subsets:
            for pname, pnum in subset.ports or [("", 0)]:
                lst: List[Tuple[str, int]] = []
                for addr in subset.addresses:
                    lst.append((addr.ip or addr.target_pod, pnum))
                # route by number AND name: kube-proxy keys rules by
                # service port number; names are aliases
                for port_id in {pname, pnum} - {""}:
                    backends_by_port.setdefault(port_id, []).extend(lst)
        if backends_by_port:
            with self._lock:
                self.legacy_routed += 1
        return backends_by_port

    @staticmethod
    def _vips(svc: v1.Service) -> List[str]:
        vips = [svc.metadata.key]  # "ns/name" — DNS-ish lookup
        if svc.spec.cluster_ip:
            vips.append(svc.spec.cluster_ip)
        return vips

    # -- the query plane ----------------------------------------------------

    def resolve(
        self, vip: str, port: object = None, client_key: str = ""
    ) -> Optional[Tuple[str, int]]:
        """One backend for vip:port. iptables mode: round-robin (the
        statistical chain). ipvs mode: the configured scheduler — "lc"
        picks the backend with the fewest tracked connections (pair with
        release() when the connection ends). ClientIP affinity overrides
        both with a stable hash."""
        with self._lock:
            backends = self._table.get((vip, port))
            if backends is None and port is None:
                # unique port fallback: a service with one port resolves
                # without naming it
                cands = [
                    v
                    for (k, p), v in self._table.items()
                    if k == vip and p is not None
                ]
                backends = cands[0] if len(cands) == 1 else None
            if not backends:
                return None
            if self._affinity.get(vip, "") == "ClientIP" and client_key:
                i = zlib.crc32(client_key.encode()) % len(backends)
            elif self.mode == "ipvs" and self.ipvs_scheduler == "lc":
                i = min(
                    range(len(backends)),
                    key=lambda j: (self._conns.get(backends[j], 0), j),
                )
            else:
                n = self._rr.get((vip, port), 0)
                self._rr[(vip, port)] = n + 1
                i = n % len(backends)
            chosen = backends[i]
            if self.mode == "ipvs":
                self._conns[chosen] = self._conns.get(chosen, 0) + 1
            return chosen

    def release(self, backend: Tuple[str, int]) -> None:
        """ipvs connection tracking: the connection to `backend` ended."""
        with self._lock:
            c = self._conns.get(backend, 0) - 1
            if c <= 0:
                self._conns.pop(backend, None)
            else:
                self._conns[backend] = c

    def endpoints_of(self, vip: str, port: object = None) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._table.get((vip, port), []))

    def wait_synced(self, timeout: float = 5.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.syncs > 0 and not self._dirty.is_set():
                    return True
            time.sleep(0.01)
        return False


class ClusterIPAllocator:
    """Admit hook: assigns a virtual ClusterIP from a /16 at Service create —
    the in-process stand-in for the apiserver's service IP allocator
    (reference pkg/registry/core/service ipallocator)."""

    def __init__(self, prefix: str = "10.96"):
        self.prefix = prefix
        self._next = itertools.count(1)
        self._lock = threading.Lock()

    def __call__(self, verb: str, kind: str, obj) -> None:
        if verb != "create" or kind != "services":
            return
        if getattr(obj.spec, "cluster_ip", ""):
            return
        with self._lock:
            n = next(self._next)
        obj.spec.cluster_ip = f"{self.prefix}.{(n >> 8) & 0xFF}.{n & 0xFF}"
