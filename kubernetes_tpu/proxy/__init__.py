from .proxy import ClusterIPAllocator, Proxier  # noqa: F401
