"""Benchmark harness: in-process topology, throughput + latency collection.

Mirrors test/integration/scheduler_perf (util.go:55 mustSetupScheduler,
:210-251 throughputCollector): in-memory API store + real scheduler + real
informers, no kubelets (binding is acknowledged by the store, the moral
equivalent of the fake PV controller / hollow-node trick). Reports
sustained throughput (scheduled pods per second over the measurement
window) and the latency histograms the reference collects.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.objects import Pod
from ..client.apiserver import APIServer
from ..scheduler import KubeSchedulerConfiguration, Scheduler
from ..utils.metrics import metrics
from ..utils.tracing import tracer
from .workloads import WorkloadConfig, build_workload


@dataclass
class BenchResult:
    workload: str
    num_nodes: int
    num_measured_pods: int
    duration_s: float
    throughput_pods_per_s: float
    scheduled: int
    unscheduled: int
    e2e_p50_ms: float = 0.0
    e2e_p90_ms: float = 0.0
    e2e_p99_ms: float = 0.0
    algo_p99_ms: float = 0.0
    # per-batch stage breakdown (sums over the measurement window)
    encode_total_s: float = 0.0
    kernel_total_s: float = 0.0
    n_batches: int = 0
    # pipeline amortization: device->host readbacks per launched wave batch
    # (< 1.0 means the tunnel RTT is being shared across batches)
    n_readbacks: int = 0
    readbacks_per_batch: float = 0.0
    # device-side ("algo-only") latency: wall of the kernel stage — device
    # compute + the one result sync — per readback (p50/p99) and averaged
    # per scheduled pod. Subtracting the measured readback RTT isolates the
    # algorithm from the deployment's tunnel (VERDICT r3 weak #7).
    kernel_cycle_p50_ms: float = 0.0
    kernel_cycle_p99_ms: float = 0.0
    kernel_per_pod_ms: float = 0.0
    # wave pipelining on the generational snapshot: configured depth and
    # the high-water mark of batches concurrently in flight (≥2 is the
    # pipelined-wave acceptance bar — one wave's device time overlapping
    # another's readback/bind instead of serializing on a device lock)
    pipeline_depth: int = 0
    max_waves_inflight: int = 0
    samples: List[int] = field(default_factory=list)  # scheduled count / 100ms

    def to_dict(self) -> dict:
        d = self.__dict__.copy()
        d.pop("samples", None)
        return d


def run_benchmark(
    cfg: WorkloadConfig,
    sched_config: Optional[KubeSchedulerConfiguration] = None,
    timeout_s: float = 300.0,
    quiet: bool = True,
    presize_nodes: Optional[int] = None,
    xplane_dir: Optional[str] = None,
) -> BenchResult:
    """xplane_dir: capture a jax-profiler (XPlane/TensorBoard) trace of the
    measured window — the device-side profiling hook SURVEY §5 calls for
    (the reference's /debug/pprof analogue for the TPU data plane). View
    with TensorBoard or xprof."""
    metrics.reset()
    server = APIServer()
    scfg = sched_config or KubeSchedulerConfiguration()
    sched = Scheduler(server, scfg)
    # presize for a larger target cluster so a warm-up run compiles the same
    # kernel variant (same v_cap/n_cap) the measured run will use
    with sched.cache.lock:
        sched.cache.encoder.presize_for_cluster(presize_nodes or cfg.num_nodes)

    nodes, init_pods, factory = build_workload(cfg)
    for n in nodes:
        server.create("nodes", n)

    sched.start()
    try:
        if xplane_dir:
            import jax

            with jax.profiler.trace(xplane_dir):
                return _run_benchmark_body(
                    cfg, server, sched, init_pods, factory, timeout_s, quiet
                )
        return _run_benchmark_body(
            cfg, server, sched, init_pods, factory, timeout_s, quiet
        )
    finally:
        sched.stop()


def _run_benchmark_body(
    cfg: WorkloadConfig,
    server: APIServer,
    sched: Scheduler,
    init_pods: List[Pod],
    factory,
    timeout_s: float,
    quiet: bool,
) -> BenchResult:
    # init pods: scheduled before measurement starts (mustSetupScheduler's
    # "init pods" stage)
    for p in init_pods:
        server.create("pods", p)
    _wait_all_scheduled(server, len(init_pods), timeout_s)

    measured = [factory(i) for i in range(cfg.num_measured_pods)]
    # baseline the stage histograms so the breakdown covers only the
    # measurement window (init pods above already ran encode/kernel)
    _e0 = metrics.histogram("scheduling_stage_duration_seconds", {"stage": "encode"})
    _k0 = metrics.histogram("scheduling_stage_duration_seconds", {"stage": "kernel"})
    base_enc, base_kern, base_n = (
        (_e0.total if _e0 else 0.0),
        (_k0.total if _k0 else 0.0),
        (_k0.n if _k0 else 0),
    )
    base_batches = metrics.counter("scheduler_wave_batches_total")
    base_readbacks = metrics.counter("scheduler_wave_readbacks_total")
    # warm the kernel before the clock starts (XLA compile is one-off)
    t0 = time.monotonic()
    for p in measured:
        server.create("pods", p)
    create_done = time.monotonic()

    total_target = len(init_pods) + cfg.num_measured_pods
    samples = []
    deadline = time.monotonic() + timeout_s
    scheduled = 0
    while time.monotonic() < deadline:
        scheduled = _count_scheduled(server)
        samples.append(scheduled)
        if scheduled >= total_target:
            break
        time.sleep(0.05)
    t1 = time.monotonic()

    measured_scheduled = scheduled - len(init_pods)
    duration = t1 - t0
    thr = measured_scheduled / duration if duration > 0 else 0.0
    e2e = metrics.histogram("e2e_scheduling_duration_seconds")
    algo = metrics.histogram("scheduling_algorithm_duration_seconds")
    enc_h = metrics.histogram(
        "scheduling_stage_duration_seconds", {"stage": "encode"}
    )
    kern_h = metrics.histogram(
        "scheduling_stage_duration_seconds", {"stage": "kernel"}
    )
    n_wave_batches = int(
        metrics.counter("scheduler_wave_batches_total") - base_batches
    )
    n_readbacks = int(
        metrics.counter("scheduler_wave_readbacks_total") - base_readbacks
    )
    res = BenchResult(
        workload=cfg.name,
        num_nodes=cfg.num_nodes,
        num_measured_pods=cfg.num_measured_pods,
        duration_s=duration,
        throughput_pods_per_s=thr,
        scheduled=measured_scheduled,
        unscheduled=cfg.num_measured_pods - measured_scheduled,
        e2e_p50_ms=(e2e.quantile(0.5) * 1000 if e2e else 0.0),
        e2e_p90_ms=(e2e.quantile(0.9) * 1000 if e2e else 0.0),
        e2e_p99_ms=(e2e.quantile(0.99) * 1000 if e2e else 0.0),
        algo_p99_ms=(algo.quantile(0.99) * 1000 if algo else 0.0),
        encode_total_s=((enc_h.total if enc_h else 0.0) - base_enc),
        kernel_total_s=((kern_h.total if kern_h else 0.0) - base_kern),
        n_batches=(
            n_wave_batches
            if n_wave_batches > 0
            else ((kern_h.n if kern_h else 0) - base_n)
        ),
        n_readbacks=n_readbacks,
        readbacks_per_batch=(
            n_readbacks / n_wave_batches if n_wave_batches > 0 else 0.0
        ),
        # quantiles over the MEASURED window only (samples past base_n):
        # the init-pod stage's compile-laden cycles would otherwise own p99
        kernel_cycle_p50_ms=(
            kern_h.quantiles_since(base_n, (0.5,))[0] * 1000 if kern_h else 0.0
        ),
        kernel_cycle_p99_ms=(
            kern_h.quantiles_since(base_n, (0.99,))[0] * 1000 if kern_h else 0.0
        ),
        kernel_per_pod_ms=(
            ((kern_h.total if kern_h else 0.0) - base_kern)
            / measured_scheduled
            * 1000
            if measured_scheduled > 0
            else 0.0
        ),
        pipeline_depth=sched._pipeline_depth,
        max_waves_inflight=int(
            metrics.gauge("scheduler_wave_inflight_max") or 0
        ),
        samples=samples,
    )
    if not quiet:
        print(
            f"{cfg.name}/{cfg.num_nodes}: {thr:.0f} pods/s "
            f"({measured_scheduled}/{cfg.num_measured_pods} in {duration:.2f}s; "
            f"create took {create_done - t0:.2f}s), "
            f"e2e p99 {res.e2e_p99_ms:.1f}ms"
        )
    return res


@dataclass
class LatencyResult:
    """Steady-state per-pod latency: pods injected at a fixed rate below
    saturation, latency = queue entry → bound (incl. queue wait). This is
    the honest p99 the burst-throughput run can't show (its per-pod latency
    is dominated by the batch former's deliberate batching window).
    Metric semantics: reference pod_scheduling_duration_seconds /
    e2e_scheduling_duration_seconds (scheduler_perf util.go:127-195)."""

    workload: str
    num_nodes: int
    rate_pods_per_s: float
    scheduled: int
    pod_p50_ms: float
    pod_p90_ms: float
    pod_p99_ms: float
    cycle_p50_ms: float
    cycle_p99_ms: float
    # where the pod latency lives: time-in-queue (queue entry → cycle
    # start, from the real per-pod "queue" spans) vs time-in-flight (the
    # in-cycle e2e histogram). pod_* ≈ queue_wait_* + in_flight_* at the
    # mean; the percentiles are each distribution's own, not a sum.
    queue_wait_p50_ms: float = 0.0
    queue_wait_p99_ms: float = 0.0
    in_flight_p50_ms: float = 0.0
    in_flight_p99_ms: float = 0.0
    # split-phase readback amortization: host-BLOCKING device syncs per
    # bound pod over the measured window (< 1.0 means most binds consumed
    # an already-landed async transfer; the r17 acceptance metric)
    readbacks_per_bind: float = 0.0
    # wave pipelining over the measured window (see BenchResult)
    pipeline_depth: int = 0
    max_waves_inflight: int = 0
    # per-stage waterfall from REAL per-pod spans (utils/tracing.py):
    # stage -> {count, total_s, p50_ms, p99_ms}, waterfall order
    stage_waterfall: Optional[dict] = None
    # mean per-trace in-cycle stage sum over the e2e histogram mean —
    # the reconciliation check (acceptance: within 5% of 1.0)
    waterfall_vs_e2e: float = 0.0
    # the p99 exemplar's trace id + its full rendered trace: "what is
    # the p99" answered with the actual pod's waterfall
    p99_trace_id: str = ""
    p99_trace: Optional[dict] = None


def run_latency_benchmark(
    cfg: WorkloadConfig,
    rate_pods_per_s: float,
    n_pods: int = 1000,
    sched_config: Optional[KubeSchedulerConfiguration] = None,
    timeout_s: float = 120.0,
    presize_nodes: Optional[int] = None,
) -> LatencyResult:
    """Inject pods one at a time at a fixed rate and report per-pod latency
    percentiles. The rate should be well below the burst throughput so the
    queue never backs up (latency is then scheduling cost, not queue depth)."""
    metrics.reset()
    tracer.reset()
    server = APIServer()
    scfg = sched_config or KubeSchedulerConfiguration()
    sched = Scheduler(server, scfg)
    with sched.cache.lock:
        sched.cache.encoder.presize_for_cluster(presize_nodes or cfg.num_nodes)

    nodes, init_pods, factory = build_workload(cfg)
    for n in nodes:
        server.create("nodes", n)
    sched.start()
    try:
        for p in init_pods:
            server.create("pods", p)
        _wait_all_scheduled(server, len(init_pods), timeout_s)

        # warm both padded-batch kernel variants (single pod → small bucket)
        # so the measured window sees no XLA compiles
        warm = factory(10**6)
        server.create("pods", warm)
        _wait_all_scheduled(server, len(init_pods) + 1, timeout_s)
        metrics.reset()
        # trace window matches the metrics window: the waterfall must
        # describe the measured pods, not init/warmup cycles
        tracer.reset()
        # the reset wiped the inflight-max gauge, but the scheduler only
        # republishes it when the peak GROWS — zero the peak too, or the
        # measured window can never re-reach the warmup burst's depth and
        # max_waves_inflight reads 0 forever
        sched._wave_inflight_peak = 0

        interval = 1.0 / rate_pods_per_s
        t_next = time.monotonic()
        for i in range(n_pods):
            server.create("pods", factory(i))
            t_next += interval
            pause = t_next - time.monotonic()
            if pause > 0:
                time.sleep(pause)
        deadline = time.monotonic() + timeout_s
        target = len(init_pods) + 1 + n_pods
        while time.monotonic() < deadline:
            if _count_scheduled(server) >= target:
                break
            time.sleep(0.02)
        scheduled = _count_scheduled(server) - len(init_pods) - 1
    finally:
        sched.stop()

    pod_h = metrics.histogram("pod_scheduling_duration_seconds")
    e2e_h = metrics.histogram("e2e_scheduling_duration_seconds")
    q = lambda h, p: (h.quantile(p) * 1000 if h else 0.0)  # noqa: E731
    waterfall, vs_e2e = _stage_waterfall(e2e_h)
    queue_stats = tracer.stage_stats(kind="pod").get("queue") or {}
    blocking = metrics.counter("scheduler_wave_readbacks_blocking_total")
    p99_tid, p99_trace = "", None
    if e2e_h is not None:
        ex = e2e_h.exemplar_near(0.99)
        if ex is not None:
            p99_tid = ex[1]
            p99_trace = tracer.get(p99_tid)
    return LatencyResult(
        workload=cfg.name,
        num_nodes=cfg.num_nodes,
        rate_pods_per_s=rate_pods_per_s,
        scheduled=scheduled,
        pod_p50_ms=q(pod_h, 0.5),
        pod_p90_ms=q(pod_h, 0.9),
        pod_p99_ms=q(pod_h, 0.99),
        cycle_p50_ms=q(e2e_h, 0.5),
        cycle_p99_ms=q(e2e_h, 0.99),
        queue_wait_p50_ms=float(queue_stats.get("p50_ms", 0.0)),
        queue_wait_p99_ms=float(queue_stats.get("p99_ms", 0.0)),
        in_flight_p50_ms=q(e2e_h, 0.5),
        in_flight_p99_ms=q(e2e_h, 0.99),
        readbacks_per_bind=(blocking / scheduled if scheduled > 0 else 0.0),
        pipeline_depth=sched._pipeline_depth,
        max_waves_inflight=int(
            metrics.gauge("scheduler_wave_inflight_max") or 0
        ),
        stage_waterfall=waterfall,
        waterfall_vs_e2e=vs_e2e,
        p99_trace_id=p99_tid,
        p99_trace=p99_trace,
    )


# pod-trace stages INSIDE the scheduling cycle (everything after the
# queue wait): their per-trace sum must reconcile with what the
# e2e_scheduling_duration_seconds histogram measured for the same pods.
# outage.wait is deliberately absent: only outcome=="bound" traces enter
# the numerator (below) because only those pods observe e2e — a
# ride-through "landed"/"rebound" pod never does, and its multi-second
# outage span would poison the ratio without any matching e2e sample.
_CYCLE_STAGES = (
    "encode", "device", "readback", "guard", "assume", "bind", "algo",
)


def _stage_waterfall(e2e_h) -> tuple:
    """(stage waterfall dict, mean in-cycle stage sum / e2e mean) from
    the tracer ring's completed pod traces. The ratio is the built-in
    honesty check: spans are contiguous stamps of the same wall interval
    the e2e histogram observes, so a drift past a few percent means the
    span chain has a hole (a stage nobody attributes)."""
    waterfall = tracer.stage_stats(kind="pod")
    if e2e_h is None or not e2e_h.n:
        return waterfall, 0.0
    sums = []
    for d in tracer.slowest(10**6, kind="pod"):
        stages = d.get("stages_ms", {})
        if d.get("outcome") != "bound":
            continue
        sums.append(
            sum(v for k, v in stages.items() if k in _CYCLE_STAGES) / 1e3
        )
    if not sums:
        return waterfall, 0.0
    return waterfall, (sum(sums) / len(sums)) / e2e_h.avg


@dataclass
class AutoscalerBenchResult:
    """The `autoscaler` bench workload: N pending pods against an empty
    cluster with a candidate-shape catalog — how long until the
    scale-up→provision→flush→bind loop has EVERY pod bound."""

    num_pods: int
    num_shapes: int
    scheduled: int
    time_to_all_bound_s: float
    nodes_provisioned: int
    nodes_by_group: Dict[str, int]
    simulation_passes: int
    simulation_p50_ms: float
    simulation_p99_ms: float


def run_autoscaler_benchmark(
    n_pods: int = 1000,
    pod_cpu: str = "500m",
    timeout_s: float = 300.0,
    period_s: float = 0.5,
    max_provision_per_cycle: int = 16,
) -> AutoscalerBenchResult:
    """Time-to-all-bound for a pending-pod burst served entirely by
    autoscaler-provisioned capacity (store-acked hollow nodes, like the
    throughput harness)."""
    from ..api.objects import Container, ObjectMeta, PodSpec
    from ..autoscaler import ClusterAutoscaler, NodeGroupCatalog
    from .workloads import autoscaler_candidate_shapes

    metrics.reset()
    server = APIServer()
    sched = Scheduler(server, KubeSchedulerConfiguration())
    groups = autoscaler_candidate_shapes()
    auto = ClusterAutoscaler(
        server,
        sched,
        NodeGroupCatalog(groups),
        period_s=period_s,
        max_provision_per_cycle=max_provision_per_cycle,
        scale_down_enabled=False,
    )
    for i in range(n_pods):
        server.create(
            "pods",
            Pod(
                metadata=ObjectMeta(name=f"asc-{i}"),
                spec=PodSpec(
                    containers=[Container(requests={"cpu": pod_cpu})]
                ),
            ),
        )
    sched.start()
    t0 = time.monotonic()
    auto.start()
    try:
        deadline = time.monotonic() + timeout_s
        scheduled = 0
        while time.monotonic() < deadline:
            scheduled = _count_scheduled(server)
            if scheduled >= n_pods:
                break
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
    finally:
        auto.stop()
        sched.stop()
    nodes, _ = server.list("nodes")
    by_group = {
        g.name: int(
            metrics.counter(
                "autoscaler_nodes_provisioned_total", {"group": g.name}
            )
        )
        for g in groups
    }
    sim_h = metrics.histogram("autoscaler_simulation_duration_seconds")
    passes = sum(
        v
        for _n, _l, v in metrics.snapshot_counters(
            "autoscaler_simulation_passes_total"
        )
    )
    p50, p99 = sim_h.quantiles((0.5, 0.99)) if sim_h else (0.0, 0.0)
    return AutoscalerBenchResult(
        num_pods=n_pods,
        num_shapes=len(groups),
        scheduled=scheduled,
        time_to_all_bound_s=elapsed,
        nodes_provisioned=len(nodes),
        nodes_by_group=by_group,
        simulation_passes=int(passes),
        simulation_p50_ms=p50 * 1e3,
        simulation_p99_ms=p99 * 1e3,
    )


@dataclass
class ReadpathBenchResult:
    """The `readpath` bench workload: N hollow informers (watch-cache
    fan-out clients) attached to one apiserver while an event storm
    flows. Delivery latency is enqueue→drain on a hot-sampled subset;
    fan-out throughput counts every queued client delivery."""

    n_informers: int
    n_events: int
    duration_s: float
    fanout_deliveries: int
    fanout_deliveries_per_s: float
    delivery_p50_ms: float
    delivery_p99_ms: float
    store_watchers: int  # the scale contract: must be 1
    replays: int
    slow_evicted: int


def run_readpath_benchmark(
    n_informers: int = 10000,
    n_events: int = 200,
    n_sampled: int = 64,
    drainers: int = 4,
) -> ReadpathBenchResult:
    """10k hollow informers on ONE store watch: measure p99 watch-delivery
    latency and fan-out throughput through the watch cache. Informers are
    hollow the same way kubemark nodes are — real fan-out queues, a
    shared drain pool instead of 10k threads."""
    import threading

    from ..api.objects import Container, ObjectMeta, PodSpec
    from ..apiserver.cacher import Cacher
    from ..runtime.watch import BOOKMARK

    server = APIServer()
    cacher = Cacher(server, bookmark_period_s=1.0)
    kc = cacher.cache_for("pods")
    r0 = metrics.counter("watch_cache_replays_total", {"kind": "pods"})
    s0 = metrics.counter(
        "watch_cache_slow_watchers_evicted_total", {"kind": "pods"}
    )
    watchers = [cacher.watch("pods") for _ in range(n_informers)]
    sampled = watchers[:n_sampled]
    latencies: List[float] = []
    lat_lock = threading.Lock()
    stop = threading.Event()

    def drain_loop(ws):
        while not stop.is_set():
            idle = True
            for w in ws:
                ev = w.get(timeout=0)
                while ev is not None:
                    idle = False
                    if ev.type != BOOKMARK and ev.ts:
                        with lat_lock:
                            latencies.append(time.monotonic() - ev.ts)
                    ev = w.get(timeout=0)
            if idle:
                time.sleep(0.001)

    chunk = max(1, len(sampled) // drainers)
    threads = [
        threading.Thread(
            target=drain_loop, args=(sampled[i : i + chunk],), daemon=True
        )
        for i in range(0, len(sampled), chunk)
    ]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    for i in range(n_events):
        server.create(
            "pods",
            Pod(
                metadata=ObjectMeta(name=f"rp-{i}"),
                spec=PodSpec(containers=[Container(requests={"cpu": "1m"})]),
            ),
        )
    # dispatch is synchronous into every client queue: once the cache rv
    # catches the store rv, every delivery is enqueued
    deadline = time.monotonic() + 60.0
    while kc.current_rv < server.resource_version and time.monotonic() < deadline:
        time.sleep(0.001)
    duration = time.monotonic() - t0
    # let the sampled drainers finish their queues for honest percentiles
    sdeadline = time.monotonic() + 10.0
    while time.monotonic() < sdeadline:
        with lat_lock:
            if len(latencies) >= n_events * len(sampled):
                break
        time.sleep(0.005)
    stop.set()
    for t in threads:
        t.join(timeout=2.0)
    store_watchers = server.watcher_count("pods")
    with lat_lock:
        lat = sorted(latencies)
    p50 = lat[int(0.5 * len(lat))] * 1e3 if lat else 0.0
    p99 = lat[min(int(0.99 * len(lat)), len(lat) - 1)] * 1e3 if lat else 0.0
    deliveries = n_events * n_informers
    for w in watchers:
        w.stop()
    cacher.stop()
    return ReadpathBenchResult(
        n_informers=n_informers,
        n_events=n_events,
        duration_s=duration,
        fanout_deliveries=deliveries,
        fanout_deliveries_per_s=deliveries / duration if duration else 0.0,
        delivery_p50_ms=p50,
        delivery_p99_ms=p99,
        store_watchers=store_watchers,
        replays=int(
            metrics.counter("watch_cache_replays_total", {"kind": "pods"}) - r0
        ),
        slow_evicted=int(
            metrics.counter(
                "watch_cache_slow_watchers_evicted_total", {"kind": "pods"}
            )
            - s0
        ),
    )


def _count_scheduled(server: APIServer) -> int:
    return server.count("pods", lambda p: bool(p.spec.node_name))


def _wait_all_scheduled(server: APIServer, count: int, timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if _count_scheduled(server) >= count:
            return
        time.sleep(0.05)
    raise TimeoutError("init pods did not all schedule")


@dataclass
class ServingBenchResult:
    """The `serving` bench workload: a MULTI-PROCESS frontend fleet
    behind the balancer — bind RTT through the pooled REST chain
    (client -> balancer -> frontend -> primary) and watch fan-out
    across hollow watchers attached to the frontends' own caches."""

    n_frontends: int
    n_watchers: int
    n_events: int
    n_binds: int
    duration_s: float
    bind_p50_ms: float
    bind_p99_ms: float
    delivery_p99_ms: float
    fanout_deliveries: int
    fanout_deliveries_per_s: float
    conn_opened: int
    conn_reused: int


def run_serving_benchmark(
    n_watchers: int = 100_000,
    n_frontends: int = 2,
    n_pods: int = 100,
    timeout_s: float = 240.0,
) -> ServingBenchResult:
    """Serving-tier fleet benchmark, real OS processes end to end.

    A primary apiserver and n_frontends stateless frontends are spawned
    as child processes (testing/netchaos_procs.py roles); each frontend
    attaches n_watchers/n_frontends hollow watchers to its OWN watch
    cache (the kubemark discipline: real fan-out queues, a sampled drain
    pool). The bench then drives n_pods creates + n_pods binds through
    an in-process LoadBalancerProxy on ONE pooled RESTClient, timing
    every bind POST round trip, and reads each frontend's delivery
    stats back over its /bench-stats endpoint."""
    import json as _json
    import os
    import subprocess
    import sys
    import tempfile
    import threading
    import urllib.request

    from ..api.objects import Binding, Container, Node, NodeSpec, NodeStatus, ObjectMeta, PodSpec
    from ..apiserver.client import (
        COUNTER_CONN_OPENED,
        COUNTER_CONN_REUSED,
        RESTClient,
    )
    from ..testing.netchaos import LoadBalancerProxy

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = []
    tmp_paths: List[str] = []  # stderr logs + ledger, removed in finally

    def spawn(args, tag):
        err = tempfile.NamedTemporaryFile(
            "w+", prefix=f"serving-bench-{tag}-", suffix=".log", delete=False
        )
        tmp_paths.append(err.name)
        p = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.testing.netchaos_procs",
             *args],
            cwd=repo, stdout=subprocess.PIPE, stderr=err, text=True, env=env,
        )
        err.close()  # the child holds its own duped fd
        procs.append(p)
        lines: List[str] = []

        def read():
            for line in p.stdout:
                lines.append(line.strip())

        threading.Thread(target=read, daemon=True).start()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            ready = [l for l in lines if l.startswith("READY")]
            if ready:
                return ready[0].split()
            if p.poll() is not None:
                raise RuntimeError(f"{tag} exited rc={p.returncode}")
            time.sleep(0.05)
        raise TimeoutError(f"{tag} never became ready")

    per_frontend = max(1, n_watchers // n_frontends)
    lb = None
    client = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False) as lf:
            ledger = lf.name
        tmp_paths.append(ledger)
        ready = spawn(["apiserver", "--port", "0", "--ledger", ledger],
                      "primary")
        primary_port = int(ready[2])
        primary_url = f"http://127.0.0.1:{primary_port}"
        stats_ports = []
        backends = []
        for i in range(n_frontends):
            r = spawn(
                ["frontend", "--primary", primary_url,
                 "--hollow-watchers", str(per_frontend)],
                f"frontend-{i}",
            )
            backends.append(("127.0.0.1", int(r[2])))
            stats_ports.append(int(r[3]))
        lb = LoadBalancerProxy(backends).start()
        client = RESTClient(f"http://127.0.0.1:{lb.port}", timeout=30.0)
        client.create(
            "nodes",
            Node(
                metadata=ObjectMeta(name="bench-n1", namespace=""),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable={"cpu": "512", "memory": "2Ti", "pods": 100000}
                ),
            ),
        )
        opened0 = metrics.counter(COUNTER_CONN_OPENED)
        reused0 = metrics.counter(COUNTER_CONN_REUSED)
        t0 = time.monotonic()
        bind_lat: List[float] = []
        for i in range(n_pods):
            client.create(
                "pods",
                Pod(
                    metadata=ObjectMeta(name=f"sv-{i}", namespace="default"),
                    spec=PodSpec(
                        containers=[Container(requests={"cpu": "1m"})]
                    ),
                ),
            )
        for i in range(n_pods):
            b = Binding(
                pod_name=f"sv-{i}", pod_namespace="default",
                target_node="bench-n1",
            )
            bt0 = time.monotonic()
            errs = client.bind_pods([b])
            if errs[0] is None:
                bind_lat.append(time.monotonic() - bt0)
        n_events = 2 * n_pods  # each pod: one ADDED + one bind MODIFIED

        def stats(port):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10
            ) as r:
                return _json.loads(r.read())

        # the storm ends when every frontend's cache consumed every event
        deadline = time.monotonic() + timeout_s
        snaps = []
        while time.monotonic() < deadline:
            snaps = [stats(p) for p in stats_ports]
            if all(s["cache_events"] >= n_events for s in snaps):
                break
            time.sleep(0.1)
        duration = time.monotonic() - t0
        # drain window: sampled watchers finish their queues for honest
        # percentiles
        sample_target = sum(s["sampled"] for s in snaps) * n_events
        drain_deadline = time.monotonic() + 20.0
        while time.monotonic() < drain_deadline:
            snaps = [stats(p) for p in stats_ports]
            if sum(s["drained"] for s in snaps) >= sample_target:
                break
            time.sleep(0.1)
        deliveries = sum(
            int(s["cache_events"]) * s["watchers"] for s in snaps
        )
        blat = sorted(bind_lat)
        return ServingBenchResult(
            n_frontends=n_frontends,
            n_watchers=sum(s["watchers"] for s in snaps),
            n_events=n_events,
            n_binds=len(bind_lat),
            duration_s=duration,
            bind_p50_ms=(blat[len(blat) // 2] * 1e3) if blat else 0.0,
            bind_p99_ms=(
                blat[min(int(0.99 * len(blat)), len(blat) - 1)] * 1e3
                if blat
                else 0.0
            ),
            delivery_p99_ms=max(
                (s["delivery_p99_ms"] for s in snaps), default=0.0
            ),
            fanout_deliveries=deliveries,
            fanout_deliveries_per_s=(
                deliveries / duration if duration else 0.0
            ),
            conn_opened=int(metrics.counter(COUNTER_CONN_OPENED) - opened0),
            conn_reused=int(metrics.counter(COUNTER_CONN_REUSED) - reused0),
        )
    finally:
        if client is not None:
            client.close()
        if lb is not None:
            lb.stop()
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass
        for path in tmp_paths:
            try:
                os.unlink(path)
            except OSError:
                pass


@dataclass
class RelayServingBenchResult:
    """The relay `serving` bench workload (ISSUE 20): a million-watcher
    TLS fan-out through the shared-memory watch relay. A primary plus
    n_frontends frontend processes run as real OS processes; each
    frontend publishes frames once into its ring and relay_workers
    SO_REUSEPORT worker processes carry the hollow watcher load, with a
    handful of REAL TLS watch clients sampled through a balancer for
    honest end-to-end latency percentiles. CPU seconds are per process
    so the flatness claim (frontend pays per FRAME, not per client) is
    checkable across watcher scales."""

    n_frontends: int
    n_relay_workers: int  # total across frontends
    n_watchers: int  # hollow + real, as registered by the workers
    n_real_clients: int
    n_events: int
    n_binds: int
    tls: bool
    duration_s: float
    bind_p50_ms: float
    bind_p99_ms: float
    watch_p50_ms: float  # bind POST -> real TLS client sees the MODIFIED
    watch_p99_ms: float
    fanout_deliveries: int  # conservative: events x watchers (no bookmarks)
    fanout_deliveries_per_s: float
    deliveries_measured: int  # worker-counter delta (includes bookmarks)
    evicted_slow: int
    shed: int
    frontend_cpu_s: List[float]  # per frontend process, storm window only
    worker_cpu_s: List[float]  # per relay worker process, storm window


def _proc_cpu_s(pid: int) -> float:
    """utime+stime of one process from /proc (Linux), seconds."""
    import os

    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(")", 1)[1].split()
        hz = os.sysconf("SC_CLK_TCK")
        return (int(fields[11]) + int(fields[12])) / hz
    except (OSError, IndexError, ValueError):
        return 0.0


def run_relay_serving_benchmark(
    n_watchers: int = 1_000_000,
    n_frontends: int = 2,
    relay_workers: int = 2,
    n_real_clients: int = 32,
    n_pods: int = 100,
    tls: bool = True,
    timeout_s: float = 600.0,
) -> RelayServingBenchResult:
    """Million-client serving through the watch relay, TLS end to end.

    Topology: primary apiserver -> n_frontends stateless frontends (each
    with --relay-workers fan-out processes over its shared-memory ring)
    -> hollow watchers in the workers plus n_real_clients genuine TLS
    watch streams through a LoadBalancerProxy over the relay ports.
    The bench drives n_pods creates + binds through the frontend REST
    hop (also TLS), then waits until every worker's dispatch has fanned
    the last bound rv out to all its clients. Deliveries are counted
    frames x subscribers — the economics the relay exists for."""
    import json as _json
    import math
    import os
    import subprocess
    import sys
    import tempfile
    import threading
    import urllib.request

    from ..api.objects import Binding, Container, Node, NodeSpec, NodeStatus, ObjectMeta, PodSpec
    from ..apiserver.client import RESTClient
    from ..runtime.watch import BOOKMARK
    from ..testing.netchaos import LoadBalancerProxy

    cert = key = ""
    if tls:
        from ..testing.tlsutil import ensure_self_signed

        cert, key = ensure_self_signed()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = []
    tmp_paths: List[str] = []

    def spawn(args, tag):
        err = tempfile.NamedTemporaryFile(
            "w+", prefix=f"relay-bench-{tag}-", suffix=".log", delete=False
        )
        tmp_paths.append(err.name)
        p = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.testing.netchaos_procs",
             *args],
            cwd=repo, stdout=subprocess.PIPE, stderr=err, text=True, env=env,
        )
        err.close()
        procs.append(p)
        lines: List[str] = []

        def read():
            for line in p.stdout:
                lines.append(line.strip())

        threading.Thread(target=read, daemon=True).start()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            ready = [l for l in lines if l.startswith("READY")]
            if ready:
                return p, ready[0].split()
            if p.poll() is not None:
                raise RuntimeError(f"{tag} exited rc={p.returncode}")
            time.sleep(0.05)
        raise TimeoutError(f"{tag} never became ready")

    # round the hollow split UP so worker-level floor division never
    # undershoots the requested watcher count
    target_hollow = max(0, n_watchers - n_real_clients)
    per_frontend = math.ceil(target_hollow / n_frontends)
    per_frontend = math.ceil(per_frontend / max(relay_workers, 1)) * max(
        relay_workers, 1
    )
    scheme = "https" if tls else "http"
    lb = rlb = None
    client = None
    real_clients: List = []
    real_watchers: List = []
    try:
        with tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False) as lf:
            ledger = lf.name
        tmp_paths.append(ledger)
        _p, ready = spawn(
            ["apiserver", "--port", "0", "--ledger", ledger], "primary"
        )
        primary_url = f"http://127.0.0.1:{int(ready[2])}"
        fe_pids: List[int] = []
        fe_ports: List[int] = []
        stats_ports: List[int] = []
        relay_ports: List[int] = []
        for i in range(n_frontends):
            fargs = [
                "frontend", "--primary", primary_url,
                "--relay-workers", str(relay_workers),
                "--relay-hollow", str(per_frontend),
            ]
            if tls:
                fargs += ["--tls-cert", cert, "--tls-key", key]
            p, r = spawn(fargs, f"frontend-{i}")
            fe_pids.append(p.pid)
            fe_ports.append(int(r[2]))
            stats_ports.append(int(r[3]))
            relay_ports.append(int(r[4]))
        lb = LoadBalancerProxy([("127.0.0.1", p) for p in fe_ports]).start()
        rlb = LoadBalancerProxy(
            [("127.0.0.1", p) for p in relay_ports]
        ).start()
        client = RESTClient(f"{scheme}://127.0.0.1:{lb.port}", timeout=30.0)
        client.create(
            "nodes",
            Node(
                metadata=ObjectMeta(name="bench-n1", namespace=""),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable={"cpu": "512", "memory": "2Ti", "pods": 100000}
                ),
            ),
        )

        def stats(port):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10
            ) as r:
                return _json.loads(r.read())

        # real TLS watch clients through the relay balancer: each one is
        # a genuine https stream terminated by a relay worker; they time
        # bind POST -> observed MODIFIED for end-to-end percentiles
        bind_t0: dict = {}
        wlat: List[float] = []
        wlock = threading.Lock()

        def drain(w, remaining):
            while remaining[0] > 0:
                ev = w.get(timeout=5.0)
                if ev is None:
                    if w.stopped:
                        return
                    continue
                if ev.type == BOOKMARK:
                    continue
                name = ev.object.metadata.name
                if getattr(ev.object.spec, "node_name", "") and name in bind_t0:
                    with wlock:
                        wlat.append(time.monotonic() - bind_t0[name])
                    remaining[0] -= 1

        for _ in range(n_real_clients):
            c = RESTClient(f"{scheme}://127.0.0.1:{rlb.port}", timeout=30.0)
            real_clients.append(c)
            real_watchers.append(c.watch("pods", 0))
        remainders = [[n_pods] for _ in real_watchers]
        for w, rem in zip(real_watchers, remainders):
            threading.Thread(target=drain, args=(w, rem), daemon=True).start()

        # pre-storm baselines: idle bookmark heartbeats already tick the
        # hollow counters, and frontends burned CPU warming up
        base = [stats(p) for p in stats_ports]
        base_delivered = sum(s["delivered"] for s in base)
        base_evicted = sum(s["evicted_slow"] for s in base)
        base_shed = sum(s["shed"] for s in base)
        base_fe_cpu = [_proc_cpu_s(pid) for pid in fe_pids]
        base_w_cpu = {
            w["pid"]: w["cpu_s"] for s in base for w in s["per_worker"]
        }
        actual_hollow = sum(s["hollow"] for s in base)

        t0 = time.monotonic()
        bind_lat: List[float] = []
        for i in range(n_pods):
            client.create(
                "pods",
                Pod(
                    metadata=ObjectMeta(name=f"rsv-{i}", namespace="default"),
                    spec=PodSpec(
                        containers=[Container(requests={"cpu": "1m"})]
                    ),
                ),
            )
        for i in range(n_pods):
            b = Binding(
                pod_name=f"rsv-{i}", pod_namespace="default",
                target_node="bench-n1",
            )
            bind_t0[f"rsv-{i}"] = time.monotonic()
            errs = client.bind_pods([b])
            if errs[0] is None:
                bind_lat.append(time.monotonic() - bind_t0[f"rsv-{i}"])
        n_events = 2 * n_pods
        final_rv = client.get(
            "pods", "default", f"rsv-{n_pods - 1}"
        ).metadata.resource_version

        # storm over when every worker's dispatch has fanned the final
        # bound rv out (hollow counters update in the same dispatch pass)
        deadline = time.monotonic() + timeout_s
        snaps = base
        while time.monotonic() < deadline:
            snaps = [stats(p) for p in stats_ports]
            if all(
                w["kinds"].get("pods", {}).get("last_rv", 0) >= final_rv
                for s in snaps
                for w in s["per_worker"]
            ):
                break
            time.sleep(0.2)
        duration = time.monotonic() - t0
        fe_cpu = [
            _proc_cpu_s(pid) - b0 for pid, b0 in zip(fe_pids, base_fe_cpu)
        ]
        w_cpu = [
            w["cpu_s"] - base_w_cpu.get(w["pid"], 0.0)
            for s in snaps
            for w in s["per_worker"]
        ]
        # honest percentile drain: give the sampled real streams a
        # moment to observe the tail of the storm
        drain_deadline = time.monotonic() + 30.0
        while time.monotonic() < drain_deadline:
            if all(rem[0] <= 0 for rem in remainders):
                break
            time.sleep(0.1)
        n_watchers_actual = actual_hollow + n_real_clients
        deliveries = n_events * n_watchers_actual
        measured = sum(s["delivered"] for s in snaps) - base_delivered
        blat = sorted(bind_lat)
        wl = sorted(wlat)
        return RelayServingBenchResult(
            n_frontends=n_frontends,
            n_relay_workers=n_frontends * relay_workers,
            n_watchers=n_watchers_actual,
            n_real_clients=n_real_clients,
            n_events=n_events,
            n_binds=len(bind_lat),
            tls=tls,
            duration_s=duration,
            bind_p50_ms=(blat[len(blat) // 2] * 1e3) if blat else 0.0,
            bind_p99_ms=(
                blat[min(int(0.99 * len(blat)), len(blat) - 1)] * 1e3
                if blat
                else 0.0
            ),
            watch_p50_ms=(wl[len(wl) // 2] * 1e3) if wl else 0.0,
            watch_p99_ms=(
                wl[min(int(0.99 * len(wl)), len(wl) - 1)] * 1e3
                if wl
                else 0.0
            ),
            fanout_deliveries=deliveries,
            fanout_deliveries_per_s=(
                deliveries / duration if duration else 0.0
            ),
            deliveries_measured=int(measured),
            evicted_slow=int(
                sum(s["evicted_slow"] for s in snaps) - base_evicted
            ),
            shed=int(sum(s["shed"] for s in snaps) - base_shed),
            frontend_cpu_s=[round(c, 3) for c in fe_cpu],
            worker_cpu_s=[round(c, 3) for c in w_cpu],
        )
    finally:
        for w in real_watchers:
            w.stop()
        for c in real_clients:
            try:
                c.close()
            except Exception:
                pass
        if client is not None:
            client.close()
        if lb is not None:
            lb.stop()
        if rlb is not None:
            rlb.stop()
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass
        for path in tmp_paths:
            try:
                os.unlink(path)
            except OSError:
                pass


@dataclass
class PreemptionBenchResult:
    """The `preemption` bench workload: a high-priority burst over a FULL
    cluster — every placement requires displacing lower-priority victims.
    The acceptance shape (ISSUE 15): victims resolve through the batched
    vectorized pass (select_batches stays per-wave, not per-pod; zero
    full host walks on the happy path)."""

    num_nodes: int
    burst_pods: int
    scheduled: int
    time_to_all_bound_s: float
    victims_evicted: int
    select_batches: int  # batched preempt_select launches (per-wave)
    vector_attempts: int  # preemption attempts served by the batched pass
    host_walk_fallbacks: int  # full per-pod host walks (happy path: 0)
    guard_trips: int
    oracle_divergences: int
    select_p50_ms: float
    select_p99_ms: float


def run_preemption_benchmark(
    n_nodes: int = 1000,
    burst: int = 1000,
    timeout_s: float = 600.0,
) -> PreemptionBenchResult:
    """1k-pending high-priority burst over a full 1k-node cluster: every
    node carries 4x 1-cpu priority-0 pods (pre-bound, store-acked), the
    burst pods need 2 cpu each at priority 100 — nothing places without
    victim selection. Reports time-to-all-bound plus the engine's
    batched-pass accounting."""
    from ..api import objects as v1

    metrics.reset()
    server = APIServer()
    sched = Scheduler(server, KubeSchedulerConfiguration())
    for i in range(n_nodes):
        server.create(
            "nodes",
            v1.Node(
                metadata=v1.ObjectMeta(name=f"pn{i}", namespace=""),
                status=v1.NodeStatus(
                    allocatable={"cpu": "4", "memory": "32Gi", "pods": 110}
                ),
            ),
        )
    # the resident victims arrive PRE-BOUND (store-acked like the
    # throughput harness): the bench measures displacement, not the
    # initial fill
    for i in range(n_nodes):
        for k in range(4):
            p = Pod(
                metadata=v1.ObjectMeta(name=f"low-{i}-{k}"),
                spec=v1.PodSpec(
                    containers=[v1.Container(requests={"cpu": "1"})],
                    priority=0,
                    node_name=f"pn{i}",
                ),
            )
            server.create("pods", p)
    sched.start()
    try:
        for i in range(burst):
            server.create(
                "pods",
                Pod(
                    metadata=v1.ObjectMeta(name=f"hi-{i}"),
                    spec=v1.PodSpec(
                        containers=[v1.Container(requests={"cpu": "2"})],
                        priority=100,
                    ),
                ),
            )
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        bound = 0
        while time.monotonic() < deadline:
            pods, _ = server.list("pods")
            bound = sum(
                1
                for p in pods
                if p.metadata.name.startswith("hi-") and p.spec.node_name
            )
            if bound >= burst:
                break
            time.sleep(0.25)
        elapsed = time.monotonic() - t0
    finally:
        sched.stop()

    def _count(name, label_filter=None):
        return int(
            sum(
                v
                for _n, labels, v in metrics.snapshot_counters(name)
                if label_filter is None or label_filter(labels)
            )
        )

    sel_h = metrics.histogram("scheduler_preemption_select_duration_seconds")
    p50, p99 = sel_h.quantiles((0.5, 0.99)) if sel_h else (0.0, 0.0)
    return PreemptionBenchResult(
        num_nodes=n_nodes,
        burst_pods=burst,
        scheduled=bound,
        time_to_all_bound_s=elapsed,
        victims_evicted=_count("preemption_victims_total"),
        select_batches=_count("scheduler_preemption_batches_total"),
        vector_attempts=_count("scheduler_preemption_vector_hits_total"),
        # only the reasons that actually run a full host walk count —
        # batch_saturated is a skip (no walk), retried next wave
        host_walk_fallbacks=_count(
            "scheduler_preemption_fallback_total",
            lambda labels: labels.get("reason")
            in ("oracle_reject", "kernel_error", "group_overflow"),
        ),
        guard_trips=_count("scheduler_preemption_guard_trips_total"),
        oracle_divergences=_count(
            "scheduler_preemption_oracle_divergence_total"
        ),
        select_p50_ms=p50 * 1e3,
        select_p99_ms=p99 * 1e3,
    )


@dataclass
class HeteroBenchResult:
    """The `hetero` bench workload: one pending burst autoscaled twice —
    cheapest-feasible-shape packing vs cost-blind MostAllocated — on the
    mixed-cost catalog. Equal feasibility (same pods bound), strictly
    cheaper fleet is the acceptance bar."""

    num_pods: int
    num_shapes: int
    cost_aware_scheduled: int
    cost_aware_nodes: Dict[str, int]
    cost_aware_fleet_per_hour: float
    cost_aware_time_s: float
    blind_scheduled: int
    blind_nodes: Dict[str, int]
    blind_fleet_per_hour: float
    blind_time_s: float

    @property
    def strictly_cheaper(self) -> bool:
        return (
            self.cost_aware_scheduled >= self.blind_scheduled
            and self.cost_aware_fleet_per_hour < self.blind_fleet_per_hour
        )


def run_hetero_benchmark(
    n_pods: int = 300, timeout_s: float = 300.0, period_s: float = 0.5
) -> HeteroBenchResult:
    """Run the same pending burst through the autoscaler twice on the
    mixed-cost catalog (perf/workloads.hetero_candidate_shapes):
    cost_aware=True (cheapest-feasible-shape) vs cost_aware=False (pure
    MostAllocated pack, the pre-ISSUE-15 behavior)."""
    from ..api import objects as v1
    from ..autoscaler import ClusterAutoscaler, NodeGroupCatalog
    from .workloads import hetero_candidate_shapes

    def one_arm(cost_aware: bool):
        metrics.reset()
        server = APIServer()
        sched = Scheduler(server, KubeSchedulerConfiguration())
        groups = hetero_candidate_shapes()
        auto = ClusterAutoscaler(
            server,
            sched,
            NodeGroupCatalog(groups),
            period_s=period_s,
            scale_down_enabled=False,
            cost_aware=cost_aware,
        )
        for i in range(n_pods):
            server.create(
                "pods",
                Pod(
                    metadata=v1.ObjectMeta(name=f"h-{i}"),
                    spec=v1.PodSpec(
                        containers=[v1.Container(requests={"cpu": "1"})]
                    ),
                ),
            )
        sched.start()
        t0 = time.monotonic()
        auto.start()
        try:
            deadline = time.monotonic() + timeout_s
            scheduled = 0
            while time.monotonic() < deadline:
                scheduled = _count_scheduled(server)
                if scheduled >= n_pods:
                    break
                time.sleep(0.1)
            elapsed = time.monotonic() - t0
        finally:
            auto.stop()
            sched.stop()
        nodes, _ = server.list("nodes")
        catalog = NodeGroupCatalog(groups)
        by_group: Dict[str, int] = {}
        fleet = 0.0
        for n in nodes:
            g = catalog.group_of_node(n)
            if g is not None:
                by_group[g.name] = by_group.get(g.name, 0) + 1
                fleet += g.cost_per_hour()
        return scheduled, by_group, round(fleet, 3), elapsed

    aware = one_arm(True)
    blind = one_arm(False)
    return HeteroBenchResult(
        num_pods=n_pods,
        num_shapes=len(hetero_candidate_shapes()),
        cost_aware_scheduled=aware[0],
        cost_aware_nodes=aware[1],
        cost_aware_fleet_per_hour=aware[2],
        cost_aware_time_s=round(aware[3], 3),
        blind_scheduled=blind[0],
        blind_nodes=blind[1],
        blind_fleet_per_hour=blind[2],
        blind_time_s=round(blind[3], 3),
    )


@dataclass
class TunerBenchResult:
    """The `tuner` bench workload: the policy gym driven through a
    workload-mix flip on a mixed-cost fleet. Pre-flip waves saturate
    every node (cost-undifferentiated: no arm can beat the incumbent, so
    NOTHING must promote); the flip switches to small bursts where a
    cost-aware vector provably wins — time from the flip to the
    promotion landing is the re-convergence number. The same pre-flip
    rounds run with the tuner off vs on give the steady-state overhead."""

    num_nodes: int
    pre_flip_rounds: int
    pre_flip_promotions: int
    baseline_pods_per_s: float
    tuner_on_pods_per_s: float
    overhead_pct: float
    converged: bool
    time_to_converge_s: float
    promoted_policy: str
    promoted_cost_weight: float
    promotions: int
    waves_recorded: int
    gym_passes: int
    gym_pass_p50_ms: float
    gym_pass_p99_ms: float


def run_tuner_benchmark(
    n_nodes: int = 8, rounds: int = 4, timeout_s: float = 120.0
) -> TunerBenchResult:
    """Drive the self-tuning scheduler (kubernetes_tpu/tuner) end to end.

    Topology: n_nodes/2 cheap + n_nodes/2 spendy nodes (9x cost spread),
    serial non-donating kernel path (the replayable path the gym's
    differential corpus certifies). Three measured segments:

      1. baseline arm — `rounds` full-width bursts (one 7-CPU pod per
         node), tuner OFF: scheduling throughput without the gym;
      2. tuner-on arm — the SAME bursts with the gym replaying every
         recorded wave in the background: throughput delta = steady-state
         overhead. Full-width waves use every node in every arm, so all
         candidate utilities tie and the gate must hold `default`;
      3. the flip — small 2-pod 500m bursts: a cost-aware arm now beats
         the incumbent on the $-per-hour term, and the wall clock from
         the first flipped burst to `set_score_policy` landing is the
         re-convergence time.
    """
    import numpy as np

    from ..api import objects as v1
    from ..ops.encoding import LABEL_COST_PER_HOUR
    from ..ops.lattice import SC_COST, WEIGHT_PROFILES
    from ..tuner.controller import PolicyTuner
    from ..tuner.policy import (
        COUNTER_GYM_PASSES,
        COUNTER_POLICY_PROMOTIONS,
        COUNTER_WAVES_RECORDED,
        HIST_GYM_PASS_SECONDS,
    )

    def node(name: str, cost: str) -> v1.Node:
        return v1.Node(
            metadata=v1.ObjectMeta(
                name=name, namespace="", labels={LABEL_COST_PER_HOUR: cost}
            ),
            status=v1.NodeStatus(
                allocatable={"cpu": "8", "memory": "32Gi", "pods": 110}
            ),
        )

    def topology():
        server = APIServer()
        for i in range(n_nodes // 2):
            server.create("nodes", node(f"tb-cheap-{i}", "1.0"))
        for i in range(n_nodes - n_nodes // 2):
            server.create("nodes", node(f"tb-spendy-{i}", "9.0"))
        cfg = KubeSchedulerConfiguration(
            use_wave=False,
            small_batch_host_max=0,
            pod_initial_backoff_seconds=0.2,
            pod_max_backoff_seconds=2.0,
        )
        return server, Scheduler(server, cfg)

    def one_burst(server, tag: str, size: int, cpu: str) -> None:
        names = [f"{tag}-{i}" for i in range(size)]
        for nm in names:
            server.create(
                "pods",
                Pod(
                    metadata=v1.ObjectMeta(name=nm),
                    spec=v1.PodSpec(
                        containers=[v1.Container(requests={"cpu": cpu})]
                    ),
                ),
            )
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if _count_scheduled(server) >= size:
                break
            time.sleep(0.02)
        for nm in names:
            server.delete("pods", "default", nm)
        time.sleep(0.2)  # let the informer restore capacity

    def full_width_rounds(server, tag: str) -> float:
        # untimed warmup burst: the first burst of an arm absorbs this
        # process's kernel compile at the 8-pod shape — without it the
        # first measured arm eats the compile storm and the off-vs-on
        # overhead comparison measures XLA, not the gym
        one_burst(server, f"{tag}-warm", n_nodes, "7")
        t0 = time.monotonic()
        for r in range(rounds):
            one_burst(server, f"{tag}-{r}", n_nodes, "7")
        elapsed = time.monotonic() - t0
        return (rounds * n_nodes) / max(elapsed, 1e-9)

    metrics.reset()
    profiles0 = set(WEIGHT_PROFILES)

    # segment 1: tuner OFF
    server, sched = topology()
    sched.start()
    try:
        baseline = full_width_rounds(server, "off")
    finally:
        sched.stop()

    # segments 2+3: tuner ON — same bursts, then the flip
    server, sched = topology()
    tuner = PolicyTuner(
        sched,
        server,
        period_s=0.2,
        shadow_windows=2,
        noise_floor=0.005,
        seed=7,
    )
    sched.start()
    tuner.start()
    try:
        on_rate = full_width_rounds(server, "on")
        pre_flip_promotions = int(metrics.counter(COUNTER_POLICY_PROMOTIONS))

        flip_t0 = time.monotonic()
        converged_at = None
        burst = 0
        while time.monotonic() - flip_t0 < timeout_s:
            one_burst(server, f"flip-{burst}", 2, "500m")
            burst += 1
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                if (
                    metrics.counter(COUNTER_POLICY_PROMOTIONS) > pre_flip_promotions
                    and float(np.asarray(sched._weights)[SC_COST]) > 0.0
                ):
                    converged_at = time.monotonic()
                    break
                time.sleep(0.05)
            if converged_at is not None:
                break
        promoted = sched._score_policy_name
        cost_w = float(np.asarray(sched._weights)[SC_COST])
        promotions = int(metrics.counter(COUNTER_POLICY_PROMOTIONS))
    finally:
        tuner.stop()
        sched.stop()
        for name in set(WEIGHT_PROFILES) - profiles0:
            WEIGHT_PROFILES.pop(name, None)

    h = metrics.histogram(HIST_GYM_PASS_SECONDS)
    p50, p99 = (h.quantiles([0.5, 0.99]) if h is not None else (0.0, 0.0))
    waves = int(
        metrics.counter(COUNTER_WAVES_RECORDED, {"path": "serial"})
        + metrics.counter(COUNTER_WAVES_RECORDED, {"path": "wave"})
    )
    return TunerBenchResult(
        num_nodes=n_nodes,
        pre_flip_rounds=rounds,
        pre_flip_promotions=pre_flip_promotions,
        baseline_pods_per_s=round(baseline, 1),
        tuner_on_pods_per_s=round(on_rate, 1),
        overhead_pct=round((baseline - on_rate) / max(baseline, 1e-9) * 100, 2),
        converged=converged_at is not None,
        time_to_converge_s=round(
            (converged_at - flip_t0) if converged_at is not None else -1.0, 3
        ),
        promoted_policy=promoted,
        promoted_cost_weight=round(cost_w, 4),
        promotions=promotions,
        waves_recorded=waves,
        gym_passes=int(metrics.counter(COUNTER_GYM_PASSES)),
        gym_pass_p50_ms=round(p50 * 1e3, 2),
        gym_pass_p99_ms=round(p99 * 1e3, 2),
    )


@dataclass
class DurabilityBenchResult:
    """The `durability` bench workload: raw WAL economics (ISSUE 18).

    Group-committed append throughput with the fsync contract on and
    off, the fsync latency distribution the stall watchdog monitors, and
    cold recovery time for a large log — the numbers that size the
    store's write path and its crash-restart MTTR."""

    n_records: int
    batch: int
    append_fsync_per_s: float
    append_nofsync_per_s: float
    fsync_p50_ms: float
    fsync_p99_ms: float
    recovery_s: float
    recovery_records_per_s: float
    recovered_rv: int
    native_sink: bool


def run_durability_benchmark(
    n_records: int = 50_000, batch: int = 64, fsync_records: int = 2_000
) -> DurabilityBenchResult:
    """Benchmark the WAL on a scratch directory: (1) `n_records` appends
    in `batch`-record group commits with fsync OFF (page-cache ceiling),
    (2) cold recovery of that log, (3) `fsync_records` appends with
    fsync ON plus the wal_fsync_duration_seconds p50/p99 over exactly
    this run's observations. Pods carry a realistic container spec so
    record size matches the scheduler's write mix."""
    import shutil
    import tempfile

    from ..api import objects as v1
    from ..runtime.wal import HIST_FSYNC, WriteAheadLog

    def pod(i: int) -> Pod:
        p = Pod(
            metadata=v1.ObjectMeta(name=f"bench-{i}"),
            spec=v1.PodSpec(
                containers=[v1.Container(requests={"cpu": "100m"})]
            ),
        )
        p.metadata.resource_version = i + 1
        return p

    def append_run(wal: WriteAheadLog, count: int, rv0: int = 0) -> float:
        t0 = time.monotonic()
        for start in range(0, count, batch):
            n = min(batch, count - start)
            wal.append_batch([  # graftlint: walseam-exempt(scratch bench WAL: nothing is acked against it and a sink failure must crash the bench loudly)
                (rv0 + start + k + 1, "create", "pods", pod(start + k))
                for k in range(n)
            ])
        return count / max(time.monotonic() - t0, 1e-9)

    tmp = tempfile.mkdtemp(prefix="ktpu-durability-")
    try:
        # arm 1: fsync off — the group-commit/encode ceiling
        wal = WriteAheadLog(tmp + "/nofsync", compact_every=n_records * 2,
                            fsync=False)
        nofsync_rate = append_run(wal, n_records)
        native = wal._native is not None
        wal.close()

        # arm 2: cold recovery of the 50k-record log (crash-restart MTTR)
        t0 = time.monotonic()
        rv, _objects = WriteAheadLog.recover(tmp + "/nofsync")
        recovery_s = max(time.monotonic() - t0, 1e-9)

        # arm 3: fsync on — the durability contract's real price, with
        # the latency histogram scoped to exactly this run
        h0 = metrics.histogram(HIST_FSYNC)
        n0 = h0.count if h0 is not None else 0
        wal = WriteAheadLog(tmp + "/fsync", compact_every=n_records * 2,
                            fsync=True)
        fsync_rate = append_run(wal, fsync_records)
        wal.close()
        h = metrics.histogram(HIST_FSYNC)
        p50, p99 = (
            h.quantiles_since(n0, [0.5, 0.99])
            if h is not None
            else (0.0, 0.0)
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    return DurabilityBenchResult(
        n_records=n_records,
        batch=batch,
        append_fsync_per_s=round(fsync_rate, 1),
        append_nofsync_per_s=round(nofsync_rate, 1),
        fsync_p50_ms=round(p50 * 1e3, 3),
        fsync_p99_ms=round(p99 * 1e3, 3),
        recovery_s=round(recovery_s, 3),
        recovery_records_per_s=round(rv / recovery_s, 1),
        recovered_rv=rv,
        native_sink=native,
    )


@dataclass
class DefragBenchResult:
    """The `defrag` bench workload: a deliberately fragmented fleet
    (half the nodes nearly full, half nearly empty, every pod owned by a
    satisfied ReplicaSet) handed to the verified descheduler. Acceptance
    is the consolidation contract itself: node count AND fleet $/h drop
    strictly, fragmentation drops, and every replica stays bound."""

    num_pods: int
    nodes_before: int
    nodes_after: int
    fleet_per_hour_before: float
    fleet_per_hour_after: float
    fragmentation_before: float
    fragmentation_after: float
    plans: int
    evictions: int
    aborts: int
    bound_after: int
    time_to_quiesce_s: float

    @property
    def strictly_tighter(self) -> bool:
        return (
            self.nodes_after < self.nodes_before
            and self.fleet_per_hour_after < self.fleet_per_hour_before
            and self.bound_after == self.num_pods
        )


def run_defrag_benchmark(
    n_heavy: int = 4,
    n_light: int = 4,
    heavy_pods: int = 6,
    light_pods: int = 2,
    node_cpu: int = 8,
    cost_per_hour: float = 2.0,
    timeout_s: float = 120.0,
    period_s: float = 0.1,
) -> DefragBenchResult:
    """Fragment a fleet on purpose (heavy nodes at heavy_pods/node_cpu
    utilization, light nodes at light_pods/node_cpu), pre-placed under a
    satisfied ReplicaSet so evicted pods are recreated and re-packed by
    the live scheduler, then time the descheduler's convergence."""
    from ..api import objects as v1
    from ..autoscaler import NodeGroup, NodeGroupCatalog, machine_shape
    from ..controller.evictionbudget import EvictionBudget
    from ..controller.replicaset import ReplicaSetController
    from ..descheduler import Descheduler
    from ..ops.encoding import LABEL_COST_PER_HOUR

    metrics.reset()
    server = APIServer()
    sched = Scheduler(server, KubeSchedulerConfiguration())
    group = NodeGroup(
        name="defrag",
        template=machine_shape(
            cpu=str(node_cpu), memory="64Gi", pods=64,
            cost_per_hour=cost_per_hour,
        ),
        max_size=n_heavy + n_light,
    )
    layout: List[tuple] = []  # (node, resident count)
    for i in range(n_heavy):
        layout.append((f"defrag-h{i}", heavy_pods))
    for i in range(n_light):
        layout.append((f"defrag-l{i}", light_pods))
    for name, _cnt in layout:
        server.create("nodes", group.make_node(name))
    n_pods = sum(c for _n, c in layout)
    rs = v1.ReplicaSet(
        metadata=v1.ObjectMeta(name="defrag-rs"),
        spec=v1.ReplicaSetSpec(
            replicas=n_pods,
            selector={"app": "defrag"},
            template=v1.PodTemplateSpec(
                metadata=v1.ObjectMeta(labels={"app": "defrag"}),
                spec=v1.PodSpec(
                    containers=[v1.Container(requests={"cpu": "1"})]
                ),
            ),
        ),
    )
    server.create("replicasets", rs)
    owners = [
        v1.OwnerReference(
            kind="ReplicaSet", name="defrag-rs", uid=rs.metadata.uid,
            controller=True,
        )
    ]
    i = 0
    for name, cnt in layout:
        for _ in range(cnt):
            server.create(
                "pods",
                Pod(
                    metadata=v1.ObjectMeta(
                        name=f"defrag-p{i}",
                        labels={"app": "defrag"},
                        owner_references=list(owners),
                    ),
                    spec=v1.PodSpec(
                        containers=[v1.Container(requests={"cpu": "1"})],
                        node_name=name,
                    ),
                ),
            )
            i += 1

    def fleet_cost() -> float:
        nodes, _ = server.list("nodes")
        total = 0.0
        for n in nodes:
            raw = n.metadata.labels.get(LABEL_COST_PER_HOUR)
            total += float(raw) if raw else 0.0
        return round(total, 3)

    rsc = ReplicaSetController(server, resync_period=0.3)
    budget = EvictionBudget(qps=200.0, burst=50)
    desch = Descheduler(
        server,
        sched,
        budget,
        catalog=NodeGroupCatalog([group]),
        period_s=period_s,
        util_threshold=(heavy_pods - 1) / node_cpu,
        max_nodes_per_plan=2,
    )
    sched.start()
    rsc.start()
    try:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if _count_scheduled(server) >= n_pods:
                break
            time.sleep(0.05)
        frag_before = sched.fragmentation_score()
        nodes_before = server.count("nodes")
        cost_before = fleet_cost()
        t0 = time.monotonic()
        desch.start()

        # quiesce: a planning pass can take seconds inside the kernel
        # simulation with nothing externally "active", so stability of
        # the observable state alone is not convergence. Converged =
        # every replica bound, no latched plan, and >= 2 FURTHER planning
        # passes since the state last moved all came back empty-handed.
        def _reject_sum() -> float:
            return sum(
                v
                for _n, l, v in metrics.snapshot_counters(
                    "descheduler_plan_rejected_total"
                )
                if l.get("reason")
                in ("no_candidates", "infeasible", "gang_strand")
            )

        state = None
        rej_at_change = _reject_sum()
        while time.monotonic() < deadline:
            cur = (
                server.count("nodes"),
                _count_scheduled(server),
                desch.executor.active,
                metrics.counter("descheduler_plans_total"),
                metrics.counter("descheduler_evictions_total"),
            )
            if cur != state:
                state = cur
                rej_at_change = _reject_sum()
            elif (
                not cur[2]
                and cur[1] >= n_pods
                and _reject_sum() - rej_at_change >= 2
            ):
                break
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
    finally:
        desch.stop()
        rsc.stop()
        sched.stop()
    aborts = sum(
        v
        for _n, _l, v in metrics.snapshot_counters(
            "descheduler_plan_aborts_total"
        )
    )
    return DefragBenchResult(
        num_pods=n_pods,
        nodes_before=nodes_before,
        nodes_after=server.count("nodes"),
        fleet_per_hour_before=cost_before,
        fleet_per_hour_after=fleet_cost(),
        fragmentation_before=round(frag_before, 4),
        fragmentation_after=round(sched.fragmentation_score(), 4),
        plans=int(metrics.counter("descheduler_plans_total")),
        evictions=int(metrics.counter("descheduler_evictions_total")),
        aborts=int(aborts),
        bound_after=_count_scheduled(server),
        time_to_quiesce_s=round(elapsed, 3),
    )
