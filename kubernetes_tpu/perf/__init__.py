"""scheduler_perf-equivalent benchmark harness."""

from .workloads import WORKLOADS, WorkloadConfig, build_workload  # noqa: F401
from .harness import run_benchmark, BenchResult  # noqa: F401
