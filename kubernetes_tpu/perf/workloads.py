"""Config-driven benchmark workloads.

Mirrors test/integration/scheduler_perf/config/performance-config.yaml: the
same suite shapes (SchedulingBasic, PodAntiAffinity, PodAffinity,
PreferredPodAffinity, TopologySpread, NodeAffinity, Gang) at 500/5000-node
scales, with the reference's benchmark node shape (110 pods, 4 CPU, 32Gi —
scheduler_test.go:52-68). Each workload yields (nodes, init_pods,
measured_pod_factory).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api.objects import (
    Affinity,
    Container,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from ..api.selectors import LabelSelector


@dataclass
class WorkloadConfig:
    name: str
    num_nodes: int = 500
    num_init_pods: int = 0
    num_measured_pods: int = 1000
    zones: int = 10


def make_bench_node(name: str, zone: str) -> Node:
    return Node(
        metadata=ObjectMeta(
            name=name,
            namespace="",
            labels={
                "topology.kubernetes.io/zone": zone,
                "kubernetes.io/hostname": name,
            },
        ),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={"cpu": "4", "memory": "32Gi", "pods": 110}),
    )


def _basic_pod(name: str, labels: Optional[dict] = None, **kw) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=PodSpec(
            containers=[Container(requests={"cpu": "100m", "memory": "128Mi"})],
            **kw,
        ),
    )


def build_workload(
    cfg: WorkloadConfig,
) -> Tuple[List[Node], List[Pod], Callable[[int], Pod]]:
    nodes = [
        make_bench_node(f"node-{i}", f"zone-{i % cfg.zones}")
        for i in range(cfg.num_nodes)
    ]
    sel = LabelSelector.make(match_labels={"app": "bench"})

    if cfg.name == "SchedulingBasic":
        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, lambda i: _basic_pod(f"pod-{i}")

    if cfg.name == "SchedulingPodAntiAffinity":
        # anti-affinity on hostname: classic one-per-node packing
        aff = Affinity(
            pod_anti_affinity=PodAntiAffinity(
                required=(
                    PodAffinityTerm(
                        label_selector=sel, topology_key="kubernetes.io/hostname"
                    ),
                )
            )
        )
        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, lambda i: _basic_pod(
            f"pod-{i}", labels={"app": "bench"}, affinity=aff
        )

    if cfg.name == "SchedulingPodAffinity":
        aff = Affinity(
            pod_affinity=PodAffinity(
                required=(
                    PodAffinityTerm(
                        label_selector=sel,
                        topology_key="topology.kubernetes.io/zone",
                    ),
                )
            )
        )
        init = [
            _basic_pod(f"init-{i}", labels={"app": "bench"})
            for i in range(max(cfg.num_init_pods, cfg.zones))
        ]
        return nodes, init, lambda i: _basic_pod(
            f"pod-{i}", labels={"app": "bench"}, affinity=aff
        )

    if cfg.name == "SchedulingPreferredPodAffinity":
        aff = Affinity(
            pod_affinity=PodAffinity(
                preferred=(
                    WeightedPodAffinityTerm(
                        1,
                        PodAffinityTerm(
                            label_selector=sel,
                            topology_key="topology.kubernetes.io/zone",
                        ),
                    ),
                )
            ),
            pod_anti_affinity=PodAntiAffinity(
                preferred=(
                    WeightedPodAffinityTerm(
                        1,
                        PodAffinityTerm(
                            label_selector=sel,
                            topology_key="kubernetes.io/hostname",
                        ),
                    ),
                )
            ),
        )
        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, lambda i: _basic_pod(
            f"pod-{i}", labels={"app": "bench"}, affinity=aff
        )

    if cfg.name == "TopologySpreading":
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key="topology.kubernetes.io/zone",
            when_unsatisfiable="DoNotSchedule",
            label_selector=sel,
        )
        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, lambda i: _basic_pod(
            f"pod-{i}",
            labels={"app": "bench"},
            topology_spread_constraints=[tsc],
        )

    if cfg.name == "SchedulingNodeAffinity":
        aff = Affinity(
            node_affinity=NodeAffinity(
                required=NodeSelector(
                    terms=(
                        NodeSelectorTerm(
                            match_expressions=(
                                NodeSelectorRequirement(
                                    "topology.kubernetes.io/zone",
                                    "In",
                                    tuple(f"zone-{z}" for z in range(cfg.zones // 2)),
                                ),
                            )
                        ),
                    )
                )
            )
        )
        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, lambda i: _basic_pod(f"pod-{i}", affinity=aff)

    if cfg.name == "SchedulingSecrets":
        # pods mounting secret volumes (performance-config.yaml
        # SchedulingSecrets): volumes ride the encode path but gate
        # nothing — isolates the spec-size cost from scheduling logic
        from ..api.objects import Volume

        def secret_factory(i: int) -> Pod:
            p = _basic_pod(f"pod-{i}")
            p.spec.volumes = [
                Volume(name=f"s{j}", secret=f"sec-{j}") for j in range(2)
            ]
            return p

        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, secret_factory

    if cfg.name == "SchedulingInTreePVs":
        # direct in-tree volumes (performance-config.yaml in-tree PV
        # variant): these pods are flagged for the HOST fallback path
        # (volume plugins — GCEPDLimits etc. — are host-side post-filters),
        # so this workload measures the fallback lane at bench scale
        from ..api.objects import GCEPersistentDiskVolumeSource, Volume

        def pv_factory(i: int) -> Pod:
            p = _basic_pod(f"pod-{i}")
            p.spec.volumes = [
                Volume(
                    name="data",
                    gce_persistent_disk=GCEPersistentDiskVolumeSource(
                        pd_name=f"disk-{i}"
                    ),
                )
            ]
            return p

        init = [_basic_pod(f"init-{i}") for i in range(cfg.num_init_pods)]
        return nodes, init, pv_factory

    if cfg.name == "Gang":
        # gang burst: groups of 50 identical pods (PodGroup-style), all
        # pending at once (BASELINE.md: 15k pending pods on 5k nodes);
        # membership/quorum per the Coscheduling plugin's contract
        from ..scheduler.framework.plugins.coscheduling import (
            GROUP_LABEL,
            MIN_MEMBER_ANNOTATION,
        )

        def factory(i: int) -> Pod:
            g = i // 50
            p = _basic_pod(
                f"pod-{i}", labels={"app": "bench", GROUP_LABEL: f"g{g}"}
            )
            p.metadata.annotations[MIN_MEMBER_ANNOTATION] = "50"
            return p

        return nodes, [], factory

    raise KeyError(f"unknown workload {cfg.name}")


def autoscaler_candidate_shapes():
    """The 4-shape NodeGroup catalog of the `autoscaler` bench workload
    (bench.py): 1k pending 500m-cpu pods against an EMPTY cluster; the
    what-if planner must mix shapes to bring them all bound. Max sizes
    give the catalog ~4x the needed capacity so shape CHOICE (not a
    capacity wall) is what's measured."""
    from ..autoscaler import NodeGroup, machine_shape

    return [
        NodeGroup(
            name="c4", template=machine_shape(cpu="4", memory="16Gi"),
            max_size=64,
        ),
        NodeGroup(
            name="c8", template=machine_shape(cpu="8", memory="32Gi"),
            max_size=32,
        ),
        NodeGroup(
            name="c16", template=machine_shape(cpu="16", memory="64Gi"),
            max_size=16,
        ),
        NodeGroup(
            name="c32", template=machine_shape(cpu="32", memory="128Gi"),
            max_size=8,
        ),
    ]


def hetero_candidate_shapes():
    """The mixed-cost fleet of the `hetero` bench workload (bench.py):
    two shape PAIRS where each pair is equally feasible for the pending
    pods but priced very differently (the heterogeneity-column labels) —
    so cheapest-feasible-shape packing is separable from capacity
    effects. Catalog order puts the expensive shape first: a
    cost-blind MostAllocated planner has no reason to prefer the cheap
    twin."""
    from ..autoscaler import NodeGroup, machine_shape

    return [
        NodeGroup(
            name="premium8",
            template=machine_shape(
                cpu="8", memory="32Gi", cost_per_hour=8.0,
                accelerator_class="tpu-v5p", energy_watts=700.0,
            ),
            max_size=48,
        ),
        NodeGroup(
            name="spot8",
            template=machine_shape(
                cpu="8", memory="32Gi", cost_per_hour=1.6,
                accelerator_class="tpu-v5e", energy_watts=300.0,
            ),
            max_size=48,
        ),
        NodeGroup(
            name="premium16",
            template=machine_shape(
                cpu="16", memory="64Gi", cost_per_hour=15.0,
                accelerator_class="tpu-v5p", energy_watts=1300.0,
            ),
            max_size=24,
        ),
        NodeGroup(
            name="spot16",
            template=machine_shape(
                cpu="16", memory="64Gi", cost_per_hour=3.1,
                accelerator_class="tpu-v5e", energy_watts=550.0,
            ),
            max_size=24,
        ),
    ]


WORKLOADS: Dict[str, WorkloadConfig] = {
    "SchedulingBasic/500": WorkloadConfig("SchedulingBasic", 500, 250, 1000),
    "SchedulingBasic/5000": WorkloadConfig("SchedulingBasic", 5000, 1000, 5000),
    "SchedulingPodAntiAffinity/500": WorkloadConfig(
        "SchedulingPodAntiAffinity", 500, 100, 400
    ),
    "SchedulingPodAntiAffinity/5000": WorkloadConfig(
        "SchedulingPodAntiAffinity", 5000, 1000, 4000
    ),
    "SchedulingPodAffinity/500": WorkloadConfig("SchedulingPodAffinity", 500, 100, 1000),
    "SchedulingPodAffinity/5000": WorkloadConfig(
        "SchedulingPodAffinity", 5000, 1000, 5000
    ),
    "SchedulingPreferredPodAffinity/5000": WorkloadConfig(
        "SchedulingPreferredPodAffinity", 5000, 1000, 5000
    ),
    "TopologySpreading/5000": WorkloadConfig("TopologySpreading", 5000, 1000, 5000),
    "SchedulingNodeAffinity/5000": WorkloadConfig(
        "SchedulingNodeAffinity", 5000, 1000, 5000
    ),
    "SchedulingSecrets/500": WorkloadConfig("SchedulingSecrets", 500, 100, 1000),
    "SchedulingSecrets/5000": WorkloadConfig("SchedulingSecrets", 5000, 1000, 5000),
    "SchedulingInTreePVs/500": WorkloadConfig("SchedulingInTreePVs", 500, 100, 400),
    "Gang/5000": WorkloadConfig("Gang", 5000, 0, 15000),
    # the reference's large density gate: 30k pods / 1000 nodes
    # (test/integration/scheduler_perf/scheduler_test.go:93-103)
    "SchedulingDensity/1000": WorkloadConfig("SchedulingBasic", 1000, 0, 30000),
}
