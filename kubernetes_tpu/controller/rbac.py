"""ClusterRole aggregation controller.

Reference: pkg/controller/clusterroleaggregation/clusterroleaggregation_controller.go
— a ClusterRole carrying an aggregationRule owns no rules of its own;
the controller unions the rules of every ClusterRole whose labels match
any of the rule's selectors and overwrites the aggregate's rules with the
result (how admin/edit/view pick up CRD-granted permissions). Any
ClusterRole event re-syncs all aggregating roles, since the changed role
may match (or no longer match) someone's selector.
"""

from __future__ import annotations

import logging
from typing import List

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.rbac")


def _rule_key(r: v1.PolicyRule):
    return (
        tuple(sorted(r.verbs)),
        tuple(sorted(r.resources)),
        tuple(sorted(r.resource_names)),
        tuple(sorted(r.api_groups)),
    )


class ClusterRoleAggregationController(WorkqueueController):
    name = "clusterrole-aggregation"
    primary_kind = "clusterroles"
    secondary_kinds = ()

    def __init__(self, server, workers: int = 1):
        super().__init__(server, workers=workers)

    def _enqueue_aggregating(self) -> None:
        for role in self.server.list("clusterroles")[0]:
            if role.aggregation_rule is not None:
                self.queue.add(role.metadata.key)

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            role = self.server.get("clusterroles", ns, name)
        except NotFound:
            # a deleted role may have fed any aggregate
            self._enqueue_aggregating()
            return
        if role.aggregation_rule is None:
            # a changed source role may match (or no longer match) any
            # aggregate's selectors: fan out from the worker, not the
            # watch thread (the reference lists-and-enqueues the same way)
            self._enqueue_aggregating()
            return
        selectors = role.aggregation_rule.cluster_role_selectors
        if not selectors:
            return
        union: List[v1.PolicyRule] = []
        seen = set()
        for other in sorted(
            self.server.list("clusterroles")[0], key=lambda r: r.metadata.name
        ):
            if other.metadata.name == role.metadata.name:
                continue  # never aggregate into yourself
            if not any(s.matches(other.metadata.labels) for s in selectors):
                continue
            for r in other.rules:
                k = _rule_key(r)
                if k not in seen:
                    seen.add(k)
                    union.append(r)
        if [_rule_key(r) for r in role.rules] == [_rule_key(r) for r in union]:
            return  # converged: nothing to propagate to chained aggregates

        def mutate(cur):
            if cur.aggregation_rule is None:
                return None
            if [_rule_key(r) for r in cur.rules] == [
                _rule_key(r) for r in union
            ]:
                return None
            cur.rules = [
                v1.PolicyRule(
                    verbs=list(r.verbs),
                    resources=list(r.resources),
                    resource_names=list(r.resource_names),
                    api_groups=list(r.api_groups),
                )
                for r in union
            ]
            return cur

        try:
            self.server.guaranteed_update("clusterroles", ns, name, mutate)
            logger.info(
                "aggregated %d rules into ClusterRole %s", len(union), name
            )
        except NotFound:
            pass
        # this role may itself feed other aggregates (admin <- edit <-
        # view chaining): fan out after an actual rules change. Fanning
        # out only on change keeps the loop convergent — a no-op sync
        # never re-enqueues
        self._enqueue_aggregating()
