"""EndpointSlice controller: sliced service endpoint publication.

Reference: pkg/controller/endpointslice (reconciler.go) — like the
Endpoints controller, but endpoints are split into EndpointSlice objects
of at most `max_endpoints_per_slice` (default 100) so huge services don't
produce megabyte Endpoints objects that every kube-proxy must re-receive
whole on any single pod change. Slices carry the
``kubernetes.io/service-name`` label; reconcile creates/updates/deletes
slices to cover exactly the backing pod set.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController, match_labels, pod_is_ready

logger = logging.getLogger("kubernetes_tpu.controller.endpointslice")

SERVICE_NAME_LABEL = "kubernetes.io/service-name"


class EndpointSliceController(WorkqueueController):
    name = "endpointslice"
    primary_kind = "services"
    secondary_kinds = ("pods",)

    def __init__(self, server, workers: int = 2, max_endpoints_per_slice: int = 100):
        super().__init__(server, workers=workers)
        self.max_per_slice = max_endpoints_per_slice

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        svcs, _ = self.server.list("services", namespace=obj.metadata.namespace)
        for s in svcs:
            if s.spec.selector and match_labels(
                s.spec.selector, obj.metadata.labels
            ):
                self.queue.add(s.metadata.key)
        return None

    def _owned_slices(self, ns: str, svc_name: str) -> List[v1.EndpointSlice]:
        slices, _ = self.server.list("endpointslices", namespace=ns)
        return [
            s
            for s in slices
            if s.metadata.labels.get(SERVICE_NAME_LABEL) == svc_name
        ]

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            svc = self.server.get("services", ns, name)
        except NotFound:
            for s in self._owned_slices(ns, name):
                try:
                    self.server.delete("endpointslices", ns, s.metadata.name)
                except NotFound:
                    pass
            return
        if not svc.spec.selector:
            return

        pods, _ = self.server.list("pods", namespace=ns)
        endpoints = [
            v1.Endpoint(
                addresses=[p.status.pod_ip] if p.status.pod_ip else [],
                ready=pod_is_ready(p),
                target_pod=p.metadata.key,
                node_name=p.spec.node_name,
            )
            for p in sorted(pods, key=lambda p: p.metadata.name)
            if p.metadata.deletion_timestamp is None
            and match_labels(svc.spec.selector, p.metadata.labels)
            and p.spec.node_name
        ]
        # slice the endpoint set (reconciler.go: fill slices up to max)
        want: List[List[v1.Endpoint]] = [
            endpoints[i : i + self.max_per_slice]
            for i in range(0, len(endpoints), self.max_per_slice)
        ] or []
        have = sorted(self._owned_slices(ns, name), key=lambda s: s.metadata.name)

        for i, chunk in enumerate(want):
            slice_name = f"{name}-{i}"
            desired_ports = list(svc.spec.ports)

            def mutate(cur, _chunk=chunk, _ports=desired_ports):
                if cur.endpoints == _chunk and cur.ports == _ports:
                    return None
                cur.endpoints = _chunk
                cur.ports = _ports
                return cur

            try:
                self.server.guaranteed_update(
                    "endpointslices", ns, slice_name, mutate
                )
            except NotFound:
                es = v1.EndpointSlice(
                    metadata=v1.ObjectMeta(
                        name=slice_name,
                        namespace=ns,
                        labels={SERVICE_NAME_LABEL: name},
                        owner_references=[
                            v1.OwnerReference(
                                kind="Service",
                                name=name,
                                uid=svc.metadata.uid,
                                controller=True,
                            )
                        ],
                    ),
                    endpoints=chunk,
                    ports=desired_ports,
                )
                try:
                    self.server.create("endpointslices", es)
                except AlreadyExists:
                    pass
        # drop surplus slices
        keep = {f"{name}-{i}" for i in range(len(want))}
        for s in have:
            if s.metadata.name not in keep:
                try:
                    self.server.delete("endpointslices", ns, s.metadata.name)
                except NotFound:
                    pass
