"""Node lifecycle controller: heartbeat monitoring, taints, eviction.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go
(:314-368): watch node Leases + NodeStatus; a node whose lease outages
exceed nodeMonitorGracePeriod goes NotReady and gets the
node.kubernetes.io/unreachable:NoExecute taint; pods on it are evicted
(deleted) after podEvictionTimeout. Recovery removes the taint.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict

from ..api import objects as v1
from ..client.apiserver import NotFound
from ..kubemark.hollow_node import NODE_LEASE_NS

logger = logging.getLogger("kubernetes_tpu.controller.nodelifecycle")

TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
# applied at node CREATE by the TaintNodesByCondition admission plugin
# (apiserver/admission.py); this controller lifts it once the node is
# Ready and re-applies it while NotReady (nodetaint/admission.go pairs
# with the lifecycle controller's taint reconciliation the same way)
TAINT_NOT_READY = "node.kubernetes.io/not-ready"


class NodeLifecycleController:
    def __init__(
        self,
        server,
        node_monitor_period: float = 1.0,
        node_monitor_grace_period: float = 40.0,
        pod_eviction_timeout: float = 60.0,
    ):
        self.server = server
        self.monitor_period = node_monitor_period
        self.grace_period = node_monitor_grace_period
        self.eviction_timeout = pod_eviction_timeout
        self._not_ready_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="nodelifecycle", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._monitor_once()
            except Exception:
                logger.exception("node monitor pass failed")
            self._stop.wait(self.monitor_period)

    def _monitor_once(self) -> None:
        now = time.time()
        nodes, _ = self.server.list("nodes")
        for node in nodes:
            name = node.metadata.name
            healthy = self._node_healthy(name, now)
            if healthy:
                # also covers a NEW node healthy from its first pass: it
                # carries the admission-time not-ready taint that only the
                # ready reconcile below lifts
                if name in self._not_ready_since or any(
                    t.key == TAINT_NOT_READY for t in node.spec.taints
                ):
                    self._not_ready_since.pop(name, None)
                    self._set_ready(name, True)
            else:
                since = self._not_ready_since.setdefault(name, now)
                if now - since >= 0:
                    self._set_ready(name, False)
                if now - since > self.eviction_timeout:
                    self._evict_pods(name, since, now)

    def _node_healthy(self, name: str, now: float) -> bool:
        try:
            lease = self.server.get("leases", NODE_LEASE_NS, name)
        except NotFound:
            return True  # no lease: node isn't lease-managed (static node)
        return now - lease.renew_time < self.grace_period

    def _set_ready(self, name: str, ready: bool) -> None:
        def mutate(node):
            changed = False
            cond = next(
                (c for c in node.status.conditions if c.type == v1.NODE_READY),
                None,
            )
            want = "True" if ready else "Unknown"
            if cond is None:
                node.status.conditions.append(
                    v1.NodeCondition(type=v1.NODE_READY, status=want)
                )
                changed = True
            elif cond.status != want:
                cond.status = want
                cond.last_transition_time = time.time()
                changed = True
            has_taint = any(
                t.key == TAINT_UNREACHABLE for t in node.spec.taints
            )
            has_nr_taint = any(
                t.key == TAINT_NOT_READY for t in node.spec.taints
            )
            if ready and (has_taint or has_nr_taint):
                node.spec.taints = [
                    t
                    for t in node.spec.taints
                    if t.key not in (TAINT_UNREACHABLE, TAINT_NOT_READY)
                ]
                changed = True
            elif not ready:
                if not has_taint:
                    node.spec.taints.append(
                        v1.Taint(TAINT_UNREACHABLE, "", v1.TAINT_NO_EXECUTE)
                    )
                    changed = True
                if not has_nr_taint:
                    node.spec.taints.append(
                        v1.Taint(TAINT_NOT_READY, "", v1.TAINT_NO_SCHEDULE)
                    )
                    changed = True
            return node if changed else None

        try:
            self.server.guaranteed_update("nodes", "", name, mutate)
        except NotFound:
            pass

    def _evict_pods(self, node_name: str, since: float, now: float) -> None:
        pods, _ = self.server.list("pods")
        for pod in pods:
            if pod.spec.node_name != node_name:
                continue
            # NoExecute toleration semantics (TaintBasedEvictions) against
            # the taint this controller actually applies: an unbounded
            # MATCHING toleration (incl. the wildcard key=""+Exists
            # DaemonSet form, via Toleration.tolerates) exempts the pod;
            # bounded tolerationSeconds (e.g. DefaultTolerationSeconds
            # 300s) only DELAY eviction — the reference's
            # minTolerationTime: the SHORTEST bound wins
            taint = v1.Taint(TAINT_UNREACHABLE, "", v1.TAINT_NO_EXECUTE)
            matching = [t for t in pod.spec.tolerations if t.tolerates(taint)]
            if any(t.toleration_seconds is None for t in matching):
                continue
            if matching and now - since < min(
                t.toleration_seconds for t in matching
            ):
                continue
            try:
                self.server.delete(
                    "pods", pod.metadata.namespace, pod.metadata.name
                )
                logger.info(
                    "evicted pod %s from dead node %s",
                    pod.metadata.key,
                    node_name,
                )
            except NotFound:
                pass
