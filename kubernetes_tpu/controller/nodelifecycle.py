"""Node lifecycle controller: heartbeat monitoring, taints, eviction.

Reference: pkg/controller/nodelifecycle/node_lifecycle_controller.go
(:314-368): watch node Leases + NodeStatus; a node whose lease outages
exceed nodeMonitorGracePeriod goes NotReady and gets the
node.kubernetes.io/unreachable:NoExecute taint; pods on it are evicted
(deleted) after podEvictionTimeout. Recovery removes the taint.

Eviction-storm safeguards (the reference's zone-aware RateLimitedTimedQueue
+ partial-disruption handling, node_lifecycle_controller.go:1090
handleDisruption):

  * **rate-limited evictions**: node evictions drain through a token
    bucket (evictionLimiterQPS) — a backlog of dead nodes empties at a
    bounded rate instead of as one delete storm.
  * **partial-disruption halt**: when more than ``partial_disruption_
    threshold`` of the lease-managed nodes go unhealthy in one monitor
    pass, the likely cause is a control-plane outage (store degraded /
    partition), not mass node death — evictions HALT and NotReady
    writes back off until the fraction recovers. ``since`` timestamps
    keep accruing, so genuinely dead nodes evict (rate-limited)
    promptly after the halt lifts.
  * **degraded-store tolerance**: ready/taint writes and evictions that
    503 retryably are counted and skipped — the monitor pass never dies
    on a read-only store, and reads (list/lease checks) keep working.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Iterable, List, Tuple

from ..api import objects as v1
from ..client.apiserver import NotFound, NotPrimary
from ..kubemark.hollow_node import NODE_LEASE_NS
from ..runtime.consensus import DegradedWrites
from ..utils.metrics import metrics
from .evictionbudget import EvictionBudget

logger = logging.getLogger("kubernetes_tpu.controller.nodelifecycle")

TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
# applied at node CREATE by the TaintNodesByCondition admission plugin
# (apiserver/admission.py); this controller lifts it once the node is
# Ready and re-applies it while NotReady (nodetaint/admission.go pairs
# with the lifecycle controller's taint reconciliation the same way)
TAINT_NOT_READY = "node.kubernetes.io/not-ready"

# metrics (rendered by /metrics and the SIGUSR2 debugger dump)
GAUGE_PARTIAL_DISRUPTION = "node_lifecycle_partial_disruption"  # 1 = halted
GAUGE_UNHEALTHY_FRACTION = "node_lifecycle_unhealthy_fraction"
GAUGE_EVICTION_TOKENS = "node_lifecycle_eviction_tokens"
COUNTER_EVICTIONS = "node_lifecycle_evictions_total"
COUNTER_EVICTIONS_DEFERRED = "node_lifecycle_evictions_deferred_total"
COUNTER_READY_WRITES_DEFERRED = "node_lifecycle_ready_writes_deferred_total"
COUNTER_STORE_WRITE_FAILURES = "node_lifecycle_store_write_failures_total"


class EvictionLimiter(EvictionBudget):
    """Back-compat alias for the PR-3 token bucket, now extracted into
    controller/evictionbudget.EvictionBudget so the node lifecycle
    controller, the scheduler's preemption victim deletes, and the
    descheduler can spend ONE shared budget (three private buckets would
    let a combined storm triple the configured eviction rate)."""


class NodeLifecycleController:
    def __init__(
        self,
        server,
        node_monitor_period: float = 1.0,
        node_monitor_grace_period: float = 40.0,
        pod_eviction_timeout: float = 60.0,
        eviction_limiter_qps: float = 10.0,
        eviction_limiter_burst: int = 5,
        partial_disruption_threshold: float = 0.55,
        eviction_budget: EvictionBudget = None,
    ):
        self.server = server
        self.monitor_period = node_monitor_period
        self.grace_period = node_monitor_grace_period
        self.eviction_timeout = pod_eviction_timeout
        self.partial_disruption_threshold = partial_disruption_threshold
        # eviction_budget: a process-wide shared bucket (injected by the
        # process wiring when preemption/descheduler coexist); the
        # private-limiter default preserves standalone behavior
        self.limiter = eviction_budget or EvictionLimiter(
            eviction_limiter_qps, eviction_limiter_burst
        )
        self._not_ready_since: Dict[str, float] = {}
        self._storm = False  # partial-disruption mode (evictions halted)
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="nodelifecycle", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._monitor_once()
            except Exception:
                logger.exception("node monitor pass failed")
            self._stop.wait(self.monitor_period)

    def _monitor_once(self) -> None:
        now = time.time()
        nodes, _ = self.server.list("nodes")
        # ONE lease list per pass (was a get per node): the health verdicts
        # for the whole fleet come from one consistent read
        leases, _ = self.server.list("leases", NODE_LEASE_NS)
        lease_by_name = {l.metadata.name: l for l in leases}
        health: List[Tuple[v1.Node, bool]] = []
        managed = unhealthy = 0
        for node in nodes:
            lease = lease_by_name.get(node.metadata.name)
            if lease is None:
                healthy = True  # no lease: not lease-managed (static node)
            else:
                managed += 1
                healthy = now - lease.renew_time < self.grace_period
                if not healthy:
                    unhealthy += 1
            health.append((node, healthy))
        frac = unhealthy / managed if managed else 0.0
        # partial disruption: most of the lease-managed fleet went dark at
        # once — that is a control-plane outage signature (store degraded,
        # partition, heartbeat path down), not mass node death. Tainting
        # and evicting now would amplify the outage into a workload
        # massacre; halt instead and let `since` accrue.
        storm = managed >= 2 and frac > self.partial_disruption_threshold
        if storm != self._storm:
            logger.warning(
                "partial-disruption mode %s (%d/%d lease-managed nodes "
                "unhealthy, threshold %.0f%%): evictions %s",
                "ENTERED" if storm else "LIFTED",
                unhealthy, managed,
                self.partial_disruption_threshold * 100,
                "halted, ready-state writes backing off" if storm
                else "resume (rate-limited)",
            )
        self._storm = storm
        metrics.set_gauge(GAUGE_PARTIAL_DISRUPTION, 1.0 if storm else 0.0)
        metrics.set_gauge(GAUGE_UNHEALTHY_FRACTION, frac)
        pods_by_node = None  # ONE pod list per pass, shared across nodes
        for node, healthy in health:
            name = node.metadata.name
            if healthy:
                # also covers a NEW node healthy from its first pass: it
                # carries the admission-time not-ready taint that only the
                # ready reconcile below lifts
                if name in self._not_ready_since or any(
                    t.key == TAINT_NOT_READY for t in node.spec.taints
                ):
                    self._not_ready_since.pop(name, None)
                    self._set_ready(name, True)
                continue
            since = self._not_ready_since.setdefault(name, now)
            if storm:
                metrics.inc(COUNTER_READY_WRITES_DEFERRED)
                continue
            if now - since >= 0:
                self._set_ready(name, False)
            if now - since > self.eviction_timeout:
                if pods_by_node is None:
                    pods_by_node = self._pods_by_node()
                # toleration filtering BEFORE token acquisition: a node
                # whose pods all tolerate the taint must not burn the
                # budget of nodes with real victims, pass after pass
                victims = [
                    p
                    for p in pods_by_node.get(name, ())
                    if self._evictable(p, since, now)
                ]
                if not victims:
                    continue
                if not self.limiter.try_acquire(actor="nodelifecycle"):
                    metrics.inc(COUNTER_EVICTIONS_DEFERRED)
                    continue
                self._evict_pods(name, victims)
        metrics.set_gauge(GAUGE_EVICTION_TOKENS, self.limiter.tokens)

    def _pods_by_node(self) -> Dict[str, List[v1.Pod]]:
        pods, _ = self.server.list("pods")
        out: Dict[str, List[v1.Pod]] = {}
        for pod in pods:
            if pod.spec.node_name:
                out.setdefault(pod.spec.node_name, []).append(pod)
        return out

    def _set_ready(self, name: str, ready: bool) -> None:
        def mutate(node):
            changed = False
            cond = next(
                (c for c in node.status.conditions if c.type == v1.NODE_READY),
                None,
            )
            want = "True" if ready else "Unknown"
            if cond is None:
                node.status.conditions.append(
                    v1.NodeCondition(
                        type=v1.NODE_READY,
                        status=want,
                        last_transition_time=time.time(),
                    )
                )
                changed = True
            elif cond.status != want:
                cond.status = want
                cond.last_transition_time = time.time()
                changed = True
            has_taint = any(
                t.key == TAINT_UNREACHABLE for t in node.spec.taints
            )
            has_nr_taint = any(
                t.key == TAINT_NOT_READY for t in node.spec.taints
            )
            if ready and (has_taint or has_nr_taint):
                node.spec.taints = [
                    t
                    for t in node.spec.taints
                    if t.key not in (TAINT_UNREACHABLE, TAINT_NOT_READY)
                ]
                changed = True
            elif not ready:
                if not has_taint:
                    node.spec.taints.append(
                        v1.Taint(TAINT_UNREACHABLE, "", v1.TAINT_NO_EXECUTE)
                    )
                    changed = True
                if not has_nr_taint:
                    node.spec.taints.append(
                        v1.Taint(TAINT_NOT_READY, "", v1.TAINT_NO_SCHEDULE)
                    )
                    changed = True
            return node if changed else None

        try:
            self.server.guaranteed_update("nodes", "", name, mutate)
        except NotFound:
            pass
        except (DegradedWrites, NotPrimary):
            # read-only store: the write retries next monitor pass
            metrics.inc(COUNTER_STORE_WRITE_FAILURES)

    @staticmethod
    def _evictable(pod: v1.Pod, since: float, now: float) -> bool:
        """NoExecute toleration semantics (TaintBasedEvictions) against
        the taint this controller actually applies: an unbounded MATCHING
        toleration (incl. the wildcard key=""+Exists DaemonSet form, via
        Toleration.tolerates) exempts the pod; bounded tolerationSeconds
        (e.g. DefaultTolerationSeconds 300s) only DELAY eviction — the
        reference's minTolerationTime: the SHORTEST bound wins."""
        taint = v1.Taint(TAINT_UNREACHABLE, "", v1.TAINT_NO_EXECUTE)
        matching = [t for t in pod.spec.tolerations if t.tolerates(taint)]
        if any(t.toleration_seconds is None for t in matching):
            return False
        if matching and now - since < min(
            t.toleration_seconds for t in matching
        ):
            return False
        return True

    def _evict_pods(self, node_name: str, pods: Iterable[v1.Pod]) -> None:
        for pod in pods:
            try:
                self.server.delete(
                    "pods", pod.metadata.namespace, pod.metadata.name
                )
                metrics.inc(COUNTER_EVICTIONS)
                logger.info(
                    "evicted pod %s from dead node %s",
                    pod.metadata.key,
                    node_name,
                )
            except NotFound:
                pass
            except (DegradedWrites, NotPrimary):
                metrics.inc(COUNTER_STORE_WRITE_FAILURES)
                return  # store read-only: stop the sweep, retry next pass
