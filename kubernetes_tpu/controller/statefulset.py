"""StatefulSet controller: ordinal pods with ordered rollout.

Reference: pkg/controller/statefulset/stateful_set_control.go
(UpdateStatefulSet) — pods are named <set>-<ordinal>; OrderedReady policy
creates ordinal i only when 0..i-1 are Running, and scales down from the
highest ordinal first. Volume claim templates / revisions are out of scope
(no dynamic provisioner in this framework — documented divergence).
"""

from __future__ import annotations

import copy
import logging
import re

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController, pod_is_ready

logger = logging.getLogger("kubernetes_tpu.controller.statefulset")

_ORDINAL_RE = re.compile(r"-(\d+)$")


class StatefulSetController(WorkqueueController):
    name = "statefulset"
    primary_kind = "statefulsets"
    secondary_kinds = ("pods",)
    owner_kind = "StatefulSet"

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            st = self.server.get("statefulsets", ns, name)
        except NotFound:
            return
        pods = self.owned_pods(ns, "StatefulSet", name)
        by_ordinal = {}
        for p in pods:
            m = _ORDINAL_RE.search(p.metadata.name)
            if m:
                by_ordinal[int(m.group(1))] = p

        want = st.spec.replicas
        ordered = st.spec.pod_management_policy == "OrderedReady"

        # scale down: highest ordinal first, one at a time when ordered
        extra = sorted((o for o in by_ordinal if o >= want), reverse=True)
        for o in extra:
            self._delete_pod(by_ordinal[o])
            if ordered:
                break

        # scale up / heal: create missing ordinals in order
        for o in range(want):
            p = by_ordinal.get(o)
            if p is None:
                self._create_pod(st, o)
                if ordered:
                    break
            elif ordered and not pod_is_ready(p):
                break  # wait for this ordinal before creating the next

        ready = sum(
            1 for o, p in by_ordinal.items() if o < want and pod_is_ready(p)
        )
        current = sum(1 for o in by_ordinal if o < want)

        def mutate(cur):
            s = cur.status
            new = (current, ready, current, cur.metadata.generation)
            old = (
                s.replicas,
                s.ready_replicas,
                s.current_replicas,
                s.observed_generation,
            )
            if new == old:
                return None
            s.replicas, s.ready_replicas, s.current_replicas, s.observed_generation = new
            return cur

        try:
            self.server.guaranteed_update("statefulsets", ns, name, mutate)
        except NotFound:
            pass

    def _create_pod(self, st: v1.StatefulSet, ordinal: int) -> None:
        tmpl = st.spec.template
        pod = v1.Pod(
            metadata=v1.ObjectMeta(
                name=f"{st.metadata.name}-{ordinal}",
                namespace=st.metadata.namespace,
                labels=dict(tmpl.metadata.labels or st.spec.selector),
                owner_references=[
                    v1.OwnerReference(
                        kind="StatefulSet",
                        name=st.metadata.name,
                        uid=st.metadata.uid,
                        controller=True,
                    )
                ],
            ),
            spec=copy.deepcopy(tmpl.spec),
        )
        pod.metadata.labels["statefulset.kubernetes.io/pod-name"] = pod.metadata.name
        try:
            self.server.create("pods", pod)
        except AlreadyExists:
            pass

    def _delete_pod(self, pod: v1.Pod) -> None:
        try:
            self.server.delete("pods", pod.metadata.namespace, pod.metadata.name)
        except NotFound:
            pass
