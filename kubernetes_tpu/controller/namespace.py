"""Namespace controller: terminating namespaces drain their objects.

Reference: pkg/controller/namespace — deleting a Namespace sweeps every
namespaced resource inside it, then removes the namespace once empty.
Deletion is modeled by phase=Terminating (set by the API layer or client).
"""

from __future__ import annotations

import logging
import threading

from ..client.apiserver import NotFound

logger = logging.getLogger("kubernetes_tpu.controller.namespace")

NAMESPACED_RESOURCES = ("pods", "replicasets", "services", "persistentvolumeclaims")


class NamespaceController:
    def __init__(self, server, period: float = 1.0):
        self.server = server
        self.period = period
        self._stop = threading.Event()

    def start(self) -> None:
        threading.Thread(target=self._run, daemon=True, name="namespace").start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._sync_once()
            except Exception:
                logger.exception("namespace sync failed")
            self._stop.wait(self.period)

    def _sync_once(self) -> None:  # graftlint: degraded-ok(_run catches everything: a degraded delete aborts the pass, retried next period)
        namespaces, _ = self.server.list("namespaces")
        for ns in namespaces:
            if ns.phase != "Terminating":
                continue
            remaining = 0
            for resource in NAMESPACED_RESOURCES:
                objs, _ = self.server.list(resource, namespace=ns.metadata.name)
                for obj in objs:
                    remaining += 1
                    try:
                        self.server.delete(
                            resource, obj.metadata.namespace, obj.metadata.name
                        )
                    except NotFound:
                        pass
            if remaining == 0:
                try:
                    self.server.delete("namespaces", "", ns.metadata.name)
                    logger.info("namespace %s deleted", ns.metadata.name)
                except NotFound:
                    pass
