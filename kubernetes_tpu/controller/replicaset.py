"""ReplicaSet controller: keep spec.replicas pods alive.

Reference: pkg/controller/replicaset/replica_set.go — the canonical
informer + workqueue + reconcile loop (syncReplicaSet): diff desired vs
actual matching pods, create/delete with owner references, update status.
"""

from __future__ import annotations

import copy
import logging
import threading
import uuid
from typing import List

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from ..runtime.watch import BOOKMARK
from ..client.workqueue import RateLimitingQueue

logger = logging.getLogger("kubernetes_tpu.controller.replicaset")


class ReplicaSetController:
    # parameterized so ReplicationControllerController shares the identical
    # reconcile core (the reference implements RC as a thin wrapper over the
    # same logic, pkg/controller/replication)
    resource = "replicasets"
    owner_kind = "ReplicaSet"

    def __init__(self, server, resync_period: float = 5.0, workers: int = 2):
        self.server = server
        self.resync = resync_period
        self.queue = RateLimitingQueue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.workers = workers

    def start(self) -> None:
        t = threading.Thread(target=self._watch_loop, daemon=True, name="rs-watch")
        t.start()
        self._threads.append(t)
        for i in range(self.workers):
            w = threading.Thread(
                target=self._worker, daemon=True, name=f"rs-worker-{i}"
            )
            w.start()
            self._threads.append(w)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()

    # -- event plumbing ------------------------------------------------------

    def _watch_loop(self) -> None:
        from ..client.apiserver import list_and_watch

        def seed(sets):
            for rs in sets:
                self.queue.add(rs.metadata.key)

        rs_watch = list_and_watch(self.server, self.resource, seed)
        pod_watch = list_and_watch(self.server, "pods", lambda _p: None)
        while not self._stop.is_set():
            ev = rs_watch.get(timeout=0.2)
            if ev is not None and ev.type in ("ADDED", "MODIFIED"):
                self.queue.add(ev.object.metadata.key)
            pev = pod_watch.get(timeout=0.05)
            if pev is not None and pev.type != BOOKMARK:
                owner = next(
                    (
                        r
                        for r in pev.object.metadata.owner_references
                        if r.kind == self.owner_kind
                    ),
                    None,
                )
                if owner is not None:
                    self.queue.add(
                        f"{pev.object.metadata.namespace}/{owner.name}"
                    )
        rs_watch.stop()
        pod_watch.stop()

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                self._sync(key)
                self.queue.forget(key)
            except Exception:
                logger.exception("sync %s failed", key)
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    # -- reconcile -----------------------------------------------------------

    def _sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            rs = self.server.get(self.resource, ns, name)
        except NotFound:
            return  # GC deletes orphans
        pods, _ = self.server.list("pods", namespace=ns)
        mine = [
            p
            for p in pods
            if p.metadata.deletion_timestamp is None
            and any(
                r.kind == self.owner_kind and r.name == name
                for r in p.metadata.owner_references
            )
        ]
        want = rs.spec.replicas
        have = len(mine)
        if have < want:
            for _ in range(want - have):
                self._create_pod(rs)
        elif have > want:
            for victim in mine[: have - want]:
                try:
                    self.server.delete("pods", ns, victim.metadata.name)
                except NotFound:
                    pass

        def update_status(cur):
            ready = sum(
                1 for p in mine if p.status.phase == v1.POD_RUNNING
            )
            if (
                cur.status.replicas == max(have, want)
                and cur.status.ready_replicas == ready
            ):
                return None
            cur.status.replicas = have if have > want else want
            cur.status.ready_replicas = ready
            cur.status.observed_generation = cur.metadata.generation
            return cur

        try:
            self.server.guaranteed_update(self.resource, ns, name, update_status)
        except NotFound:
            pass

    def _create_pod(self, rs: v1.ReplicaSet) -> None:
        tmpl = rs.spec.template
        pod = v1.Pod(
            metadata=v1.ObjectMeta(
                name=f"{rs.metadata.name}-{uuid.uuid4().hex[:5]}",
                namespace=rs.metadata.namespace,
                labels=dict(tmpl.metadata.labels or rs.spec.selector),
                owner_references=[
                    v1.OwnerReference(
                        kind=self.owner_kind,
                        name=rs.metadata.name,
                        uid=rs.metadata.uid,
                        controller=True,
                    )
                ],
            ),
            spec=copy.deepcopy(tmpl.spec),
        )
        try:
            self.server.create("pods", pod)
        except AlreadyExists:
            pass


class ReplicationControllerController(ReplicaSetController):
    """ReplicationController loop: the same reconcile over the older core
    kind (pkg/controller/replication wraps the replicaset core identically)."""

    resource = "replicationcontrollers"
    owner_kind = "ReplicationController"
