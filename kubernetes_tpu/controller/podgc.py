"""Pod garbage collector + protection finalizer controllers.

1. ``PodGCController`` — reference pkg/controller/podgc/gc_controller.go:
   delete terminated pods beyond ``terminated_pod_threshold`` (oldest
   first), pods bound to nodes that no longer exist, and deletion-pending
   pods that never got scheduled (gcUnscheduledTerminating).

2. ``PVCProtectionController`` / ``PVProtectionController`` — reference
   pkg/controller/volume/{pvcprotection,pvprotection}: objects carry a
   protection finalizer while in use; deletion is deferred (the store's
   deletion_timestamp marks intent) until no live pod references the PVC /
   no claim references the PV, then stripping the finalizer completes the
   deferred deletion (store update() removes deletion-pending
   finalizer-free objects). Both are one shared state machine
   parameterized by (finalizer, in_use predicate).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.podgc")

PVC_FINALIZER = "kubernetes.io/pvc-protection"
PV_FINALIZER = "kubernetes.io/pv-protection"


class PodGCController(WorkqueueController):
    name = "podgc"
    primary_kind = "pods"
    secondary_kinds = ()

    def __init__(
        self, server, workers: int = 1, terminated_pod_threshold: int = 1000,
        tick: float = 20.0,
    ):
        # tick matches the reference's gcCheckPeriod (20s): the sweep
        # deep-copies the pod world, so it must not run hot
        super().__init__(server, workers=workers)
        self.threshold = terminated_pod_threshold
        self.tick = tick

    def primary_key_of(self, obj) -> str:
        return "gc"  # world sweep; collapse event bursts

    def start(self) -> None:
        super().start()
        self.start_ticker("podgc-tick", self.tick, lambda: self.queue.add("gc"))

    def sync(self, key: str) -> None:
        # copy-free prefilter: skip the world copy when nothing can be
        # collectable (the common steady state)
        n_terminated = self.server.count(
            "pods",
            lambda p: p.status.phase in (v1.POD_SUCCEEDED, v1.POD_FAILED)
            or p.metadata.deletion_timestamp is not None,
        )
        if n_terminated == 0:
            return
        pods, _ = self.server.list("pods")
        nodes = {n.metadata.name for n in self.server.list("nodes")[0]}
        terminated = [
            p
            for p in pods
            if p.status.phase in (v1.POD_SUCCEEDED, v1.POD_FAILED)
        ]
        # threshold GC: oldest finished pods beyond the cap
        if self.threshold > 0 and len(terminated) > self.threshold:
            doomed = sorted(
                terminated, key=lambda p: p.metadata.creation_timestamp or 0.0
            )[: len(terminated) - self.threshold]
            for p in doomed:
                self._force_delete(p)
        for p in pods:
            # orphan GC: bound to a node that no longer exists
            if p.spec.node_name and p.spec.node_name not in nodes:
                self._force_delete(p)
            # gcUnscheduledTerminating: deletion-pending and never scheduled
            # — no kubelet will ever act on it, release it now
            elif (
                p.metadata.deletion_timestamp is not None
                and not p.spec.node_name
            ):
                self._force_delete(p)

    def _force_delete(self, pod: v1.Pod) -> None:
        # plain delete: foreign finalizers still gate the actual removal
        # (their owners run cleanup and strip) — podgc must never bypass
        # another component's finalizer, it only expresses deletion intent
        try:
            self.server.delete("pods", pod.metadata.namespace, pod.metadata.name)
        except NotFound:
            pass


class _ProtectionController(WorkqueueController):
    """Shared finalizer state machine: ensure the finalizer on live
    objects; once deletion is requested, hold it until `in_use` clears,
    then strip (which completes the deferred deletion)."""

    finalizer = ""

    def in_use(self, obj) -> bool:  # pragma: no cover — abstract
        raise NotImplementedError

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            obj = self.server.get(self.primary_kind, ns, name)
        except NotFound:
            return
        if self.finalizer not in obj.metadata.finalizers:
            if obj.metadata.deletion_timestamp is None:
                def add_fin(o):
                    if self.finalizer in o.metadata.finalizers:
                        return None
                    o.metadata.finalizers.append(self.finalizer)
                    return o

                try:
                    self.server.guaranteed_update(
                        self.primary_kind, ns, name, add_fin
                    )
                except NotFound:
                    pass
            return
        if obj.metadata.deletion_timestamp is None:
            return
        if self.in_use(obj):
            return  # deletion stays deferred while referenced

        def strip(o):
            if self.finalizer not in o.metadata.finalizers:
                return None
            o.metadata.finalizers.remove(self.finalizer)
            return o

        try:
            self.server.guaranteed_update(self.primary_kind, ns, name, strip)
        except NotFound:
            pass


def _pod_blocks_pvc(pod: v1.Pod, claim_name: str) -> bool:
    """Does this pod hold the claim? Terminated pods don't (the reference
    pvc_protection excludes them); deletion-pending pods still RUNNING on a
    kubelet do (the volume is still mounted)."""
    if pod.status.phase in (v1.POD_SUCCEEDED, v1.POD_FAILED):
        return False
    return any(
        vol.persistent_volume_claim == claim_name for vol in pod.spec.volumes
    )


class PVCProtectionController(_ProtectionController):
    name = "pvc-protection"
    primary_kind = "persistentvolumeclaims"
    secondary_kinds = ("pods",)
    finalizer = PVC_FINALIZER

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        # only pod transitions touching PVC-backed volumes matter — pod
        # status churn is the hottest stream in the system, so enqueue just
        # the claims this pod references
        for vol in obj.spec.volumes:
            if vol.persistent_volume_claim:
                self.queue.add(
                    f"{obj.metadata.namespace}/{vol.persistent_volume_claim}"
                )
        return None

    def in_use(self, pvc) -> bool:
        ns, claim = pvc.metadata.namespace, pvc.metadata.name
        return (
            self.server.count(
                "pods",
                lambda p, _ns=ns, _c=claim: p.metadata.namespace == _ns
                and _pod_blocks_pvc(p, _c),
            )
            > 0
        )


class PVProtectionController(_ProtectionController):
    name = "pv-protection"
    primary_kind = "persistentvolumes"
    secondary_kinds = ("persistentvolumeclaims",)
    finalizer = PV_FINALIZER

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        if obj.spec.volume_name:
            self.queue.add(obj.spec.volume_name)
        return None

    def in_use(self, pv) -> bool:
        return bool(pv.spec.claim_ref)


class RootCACertPublisher(WorkqueueController):
    """Publish the cluster trust bundle into every namespace as the
    ``kube-root-ca.crt`` ConfigMap (pkg/controller/certificates/rootcacertpublisher).
    The bundle here is the token trust root descriptor (no x509)."""

    name = "root-ca-cert-publisher"
    primary_kind = "namespaces"
    secondary_kinds = ("configmaps",)

    CONFIGMAP = "kube-root-ca.crt"

    def __init__(self, server, workers: int = 1, ca_data: str = "tpu-cluster-trust-root"):
        super().__init__(server, workers=workers)
        self.ca_data = ca_data

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        # deleted/tampered bundle: re-publish (the reference watches the
        # configmaps too, rootcacertpublisher.go)
        if obj.metadata.name != self.CONFIGMAP:
            return None
        for ns in self.server.list("namespaces")[0]:
            if ns.metadata.name == obj.metadata.namespace:
                return ns.metadata.key
        return None

    def sync(self, key: str) -> None:
        name = key.rpartition("/")[2]
        try:
            ns_obj = self.server.get("namespaces", key.rpartition("/")[0], name)
        except NotFound:
            return
        if ns_obj.metadata.deletion_timestamp is not None:
            return
        try:
            cm = self.server.get("configmaps", name, self.CONFIGMAP)
            if cm.data.get("ca.crt") != self.ca_data:
                # tampered bundle: restore it (the reference publisher
                # updates on data mismatch, not just absence)
                def repair(cur):
                    if cur.data.get("ca.crt") == self.ca_data:
                        return None
                    cur.data["ca.crt"] = self.ca_data
                    return cur

                self.server.guaranteed_update(
                    "configmaps", name, self.CONFIGMAP, repair
                )
            return
        except NotFound:
            pass
        try:
            self.server.create(
                "configmaps",
                v1.ConfigMap(
                    metadata=v1.ObjectMeta(name=self.CONFIGMAP, namespace=name),
                    data={"ca.crt": self.ca_data},
                ),
            )
        except AlreadyExists:
            pass
        except Exception:
            logger.exception("publishing %s to %s failed", self.CONFIGMAP, name)
