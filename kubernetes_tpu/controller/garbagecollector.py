"""Garbage collector: cascade-delete objects whose owner is gone.

Reference: pkg/controller/garbagecollector — the dependency graph of
ownerReferences; orphaned dependents (owner uid no longer exists) are
deleted. Reduced to the kinds the framework serves; same observable
behavior for the scheduler-relevant cascade (ReplicaSet → Pods).
"""

from __future__ import annotations

import logging
import threading

from ..client.apiserver import NotFound

logger = logging.getLogger("kubernetes_tpu.controller.gc")

# kinds that can own / be owned, by kind string -> resource
_KIND_RESOURCES = {
    "ReplicaSet": "replicasets",
    "Pod": "pods",
    "Service": "services",
    "Deployment": "deployments",
    "Job": "jobs",
    "DaemonSet": "daemonsets",
    "StatefulSet": "statefulsets",
}
_DEPENDENT_RESOURCES = ("pods", "replicasets")


class GarbageCollector:
    def __init__(self, server, period: float = 2.0):
        self.server = server
        self.period = period
        self._stop = threading.Event()

    def start(self) -> None:
        threading.Thread(target=self._run, daemon=True, name="gc").start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._collect_once()
            except Exception:
                logger.exception("gc pass failed")
            self._stop.wait(self.period)

    def _collect_once(self) -> None:  # graftlint: degraded-ok(_run catches everything: a degraded delete aborts the pass, retried next period)
        # live uids per owner kind
        live = {}
        for kind, resource in _KIND_RESOURCES.items():
            objs, _ = self.server.list(resource)
            live[kind] = {o.metadata.uid for o in objs}
        for resource in _DEPENDENT_RESOURCES:
            objs, _ = self.server.list(resource)
            for obj in objs:
                refs = obj.metadata.owner_references
                if not refs:
                    continue
                orphaned = all(
                    ref.kind in live and ref.uid not in live[ref.kind]
                    for ref in refs
                    if ref.kind in _KIND_RESOURCES
                )
                relevant = any(ref.kind in _KIND_RESOURCES for ref in refs)
                if relevant and orphaned:
                    try:
                        self.server.delete(
                            resource, obj.metadata.namespace, obj.metadata.name
                        )
                        logger.info(
                            "gc deleted orphaned %s %s",
                            resource,
                            obj.metadata.key,
                        )
                    except NotFound:
                        pass
