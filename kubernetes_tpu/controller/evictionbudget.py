"""Process-wide eviction budget: ONE token bucket for every evictor.

Extracted from controller/nodelifecycle.py (the PR-3 eviction limiter):
three subsystems now deliberately delete healthy-looking pods — the node
lifecycle controller (dead-node drains), the scheduler's preemption path
(victim deletes), and the descheduler (consolidation waves) — and each
pacing itself against a PRIVATE bucket would let a combined storm evict
at three times the configured cluster rate. A process that runs more
than one evictor constructs one ``EvictionBudget`` and injects it into
all of them (cmd/scheduler.py does exactly this); per-actor counters
keep the shared spend attributable.

The bucket itself is the reference's flowcontrol.NewTokenBucketRateLimiter
shape (qps refill, burst headroom), unchanged from the PR-3 limiter —
``EvictionLimiter`` in nodelifecycle.py remains as a back-compat alias.
"""

from __future__ import annotations

import threading
import time

from ..utils.metrics import metrics

# metrics (rendered by /metrics and the SIGUSR2 debugger dump). The
# per-actor split is the whole point of sharing: a dry budget must be
# attributable to WHO spent it, or a preemption storm starving the
# descheduler (by design) reads like a descheduler bug.
GAUGE_BUDGET_TOKENS = "eviction_budget_tokens"
COUNTER_BUDGET_ACQUIRED = "eviction_budget_acquired_total"
COUNTER_BUDGET_DEFERRED = "eviction_budget_deferred_total"


class EvictionBudget:
    """Token bucket over evictions: at most ``qps`` per second with
    ``burst`` headroom, shared by every actor holding a reference.

    ``try_acquire(actor=...)`` labels the per-actor spend/defer counters;
    callers that predate the shared budget (or tests driving the bucket
    directly) may omit ``actor`` and get the bare-bucket behavior with
    no metric emission.
    """

    def __init__(self, qps: float = 10.0, burst: int = 5):
        if qps <= 0:
            raise ValueError(f"eviction qps must be > 0, got {qps}")
        self.qps = qps
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, now: float = None, actor: str = "") -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._tokens = min(
                float(self.burst), self._tokens + (now - self._last) * self.qps
            )
            self._last = now
            ok = self._tokens >= 1.0
            if ok:
                self._tokens -= 1.0
            tokens = self._tokens
        if actor:
            if ok:
                metrics.inc(COUNTER_BUDGET_ACQUIRED, {"actor": actor})
            else:
                metrics.inc(COUNTER_BUDGET_DEFERRED, {"actor": actor})
            metrics.set_gauge(GAUGE_BUDGET_TOKENS, tokens)
        return ok

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


def eviction_budget_health_lines() -> list:
    """Shared-budget counters/gauge rendered for the SIGUSR2 debugger
    dump — empty when no budget-labeled acquire ran in this process."""
    lines = []
    for series in (
        metrics.snapshot_gauges("eviction_budget_"),
        metrics.snapshot_counters("eviction_budget_"),
    ):
        for name, labels, value in series:
            lines.append(metrics.format_series_line(name, labels, value))
    return lines
