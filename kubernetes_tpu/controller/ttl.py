"""TTL controllers.

1. ``TTLController`` — reference pkg/controller/ttl/ttl_controller.go:
   annotate every node with ``node.alpha.kubernetes.io/ttl``, the
   secret/configmap kubelet-cache TTL, stepped by cluster size (0s under
   100 nodes, 15s under 500, 30s under 1000, 60s above — the reference's
   ttlBoundaries).

2. ``TTLAfterFinishedController`` — reference
   pkg/controller/ttlafterfinished/ttlafterfinished_controller.go: delete
   finished Jobs ``spec.ttl_seconds_after_finished`` seconds after they
   complete or fail.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.ttl")

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"
# (max cluster size for this tier, ttl seconds) — ttl_controller.go boundaries
TTL_BOUNDARIES = [(100, 0), (500, 15), (1000, 30), (1 << 62, 60)]


def ttl_for_cluster_size(n: int) -> int:
    for bound, ttl in TTL_BOUNDARIES:
        if n <= bound:
            return ttl
    return 60


class TTLController(WorkqueueController):
    name = "ttl"
    primary_kind = "nodes"
    secondary_kinds = ()

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")  # store key carries the namespace
        want = str(ttl_for_cluster_size(self.server.count("nodes")))

        def mutate(node):
            if node.metadata.annotations.get(TTL_ANNOTATION) == want:
                return None
            node.metadata.annotations[TTL_ANNOTATION] = want
            return node

        try:
            self.server.guaranteed_update("nodes", ns, name, mutate)
        except NotFound:
            pass


class TTLAfterFinishedController(WorkqueueController):
    name = "ttlafterfinished"
    primary_kind = "jobs"
    secondary_kinds = ()

    def __init__(self, server, workers: int = 1, tick: float = 1.0):
        super().__init__(server, workers=workers)
        self.tick = tick

    def start(self) -> None:
        super().start()
        # expirations fire by time, not by watch events
        self.start_ticker("ttlafterfinished-tick", self.tick, self._enqueue_ttl_jobs)

    def _enqueue_ttl_jobs(self) -> None:
        jobs, _ = self.server.list("jobs")
        for j in jobs:
            if getattr(j.spec, "ttl_seconds_after_finished", None) is not None:
                self.queue.add(j.metadata.key)

    @staticmethod
    def _finish_time(job: v1.Job) -> Optional[float]:
        times = [
            c.last_transition_time
            for c in job.status.conditions
            if c.type in ("Complete", "Failed") and c.status == "True"
        ]
        return max(times) if times else None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            job = self.server.get("jobs", ns, name)
        except NotFound:
            return
        ttl = getattr(job.spec, "ttl_seconds_after_finished", None)
        if ttl is None:
            return
        finished = self._finish_time(job)
        if finished is None:
            return
        if time.time() - finished >= ttl:
            try:
                self.server.delete("jobs", ns, name)
            except NotFound:
                pass
