"""Node IPAM controller: allocate a pod CIDR per node from the cluster CIDR.

Reference: pkg/controller/nodeipam (range_allocator.go) — every node gets
one /node_mask_size block out of --cluster-cidr; blocks release on node
deletion and are never double-allocated (the allocator re-syncs its bitmap
from live nodes on startup, the crash-only pattern).
"""

from __future__ import annotations

import ipaddress
import logging
import threading
from typing import Optional, Set

from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.nodeipam")


class NodeIpamController(WorkqueueController):
    name = "nodeipam"
    primary_kind = "nodes"
    secondary_kinds = ()

    def __init__(
        self,
        server,
        workers: int = 1,
        cluster_cidr: str = "10.244.0.0/16",
        node_mask_size: int = 24,
    ):
        super().__init__(server, workers=workers)
        self.cluster = ipaddress.ip_network(cluster_cidr)
        self._all = list(self.cluster.subnets(new_prefix=node_mask_size))
        self._alloc_lock = threading.Lock()
        self._used: Optional[Set[str]] = None  # lazy: rebuilt from live nodes

    def _rebuild_used(self) -> Set[str]:
        nodes, _ = self.server.list("nodes")
        return {n.spec.pod_cidr for n in nodes if n.spec.pod_cidr}

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            node = self.server.get("nodes", ns, name)
        except NotFound:
            # released blocks return to the pool on the next allocation's
            # rebuild (allocator state is derived, never authoritative)
            with self._alloc_lock:
                self._used = None
            return
        if node.spec.pod_cidr:
            return
        with self._alloc_lock:
            if self._used is None:
                self._used = self._rebuild_used()
            cidr = next(
                (str(s) for s in self._all if str(s) not in self._used), None
            )
            if cidr is None:
                logger.error("cluster CIDR %s exhausted", self.cluster)
                return
            self._used.add(cidr)

        def mutate(n):
            if n.spec.pod_cidr:
                return None  # raced another allocation; keep theirs
            n.spec.pod_cidr = cidr
            return n

        try:
            final = self.server.guaranteed_update("nodes", ns, name, mutate)
            if final.spec.pod_cidr != cidr:
                # lost the race: release the block we reserved or it would
                # leak out of the pool permanently
                with self._alloc_lock:
                    if self._used is not None:
                        self._used.discard(cidr)
        except NotFound:
            with self._alloc_lock:
                if self._used is not None:
                    self._used.discard(cidr)
