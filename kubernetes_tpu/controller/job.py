"""Job controller: run pods to completion with parallelism and backoff.

Reference: pkg/controller/job/job_controller.go (syncJob) — maintain up to
spec.parallelism active pods until spec.completions pods have succeeded;
past spec.backoffLimit failures the Job is marked Failed and active pods
are removed. completions=None means "any one success completes the job"
(the reference's non-indexed, nil-completions mode).
"""

from __future__ import annotations

import copy
import logging
import time
import uuid

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.job")

COND_COMPLETE = "Complete"
COND_FAILED = "Failed"


class JobController(WorkqueueController):
    name = "job"
    primary_kind = "jobs"
    secondary_kinds = ("pods",)
    owner_kind = "Job"

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            job = self.server.get("jobs", ns, name)
        except NotFound:
            return
        if any(
            c.type in (COND_COMPLETE, COND_FAILED) and c.status == "True"
            for c in job.status.conditions
        ):
            return  # terminal

        pods = self.owned_pods(ns, "Job", name)
        active = [
            p
            for p in pods
            if p.status.phase not in (v1.POD_SUCCEEDED, v1.POD_FAILED)
        ]
        succeeded = sum(1 for p in pods if p.status.phase == v1.POD_SUCCEEDED)
        failed = sum(1 for p in pods if p.status.phase == v1.POD_FAILED)

        deadline_exceeded = (
            job.spec.active_deadline_seconds is not None
            and job.status.start_time is not None
            and time.time() - job.status.start_time
            > job.spec.active_deadline_seconds
        )
        if failed > job.spec.backoff_limit or deadline_exceeded:
            for p in active:
                self._delete_pod(p)
            reason = (
                "DeadlineExceeded" if deadline_exceeded else "BackoffLimitExceeded"
            )
            self._update_status(
                job, 0, succeeded, failed, condition=(COND_FAILED, reason)
            )
            return

        completions = job.spec.completions
        if completions is None:
            done = succeeded > 0
            want_active = 0 if done else job.spec.parallelism
        else:
            remaining = max(0, completions - succeeded)
            done = remaining == 0
            want_active = min(job.spec.parallelism, remaining)

        if done:
            for p in active:
                self._delete_pod(p)
            self._update_status(
                job, 0, succeeded, failed, condition=(COND_COMPLETE, "")
            )
            return

        if len(active) < want_active:
            for _ in range(want_active - len(active)):
                self._create_pod(job)
        elif len(active) > want_active:
            for p in active[: len(active) - want_active]:
                self._delete_pod(p)
        self._update_status(job, max(len(active), want_active), succeeded, failed)

    def _create_pod(self, job: v1.Job) -> None:
        tmpl = job.spec.template
        spec = copy.deepcopy(tmpl.spec)
        if spec.restart_policy == "Always":
            spec.restart_policy = "OnFailure"  # jobs must terminate
        pod = v1.Pod(
            metadata=v1.ObjectMeta(
                name=f"{job.metadata.name}-{uuid.uuid4().hex[:5]}",
                namespace=job.metadata.namespace,
                labels=dict(
                    tmpl.metadata.labels
                    or job.spec.selector
                    or {"job-name": job.metadata.name}
                ),
                owner_references=[
                    v1.OwnerReference(
                        kind="Job",
                        name=job.metadata.name,
                        uid=job.metadata.uid,
                        controller=True,
                    )
                ],
            ),
            spec=spec,
        )
        try:
            self.server.create("pods", pod)
        except AlreadyExists:
            pass

    def _delete_pod(self, pod: v1.Pod) -> None:
        try:
            self.server.delete("pods", pod.metadata.namespace, pod.metadata.name)
        except NotFound:
            pass

    def _update_status(
        self, job: v1.Job, active: int, succeeded: int, failed: int, condition=None
    ) -> None:
        def mutate(cur):
            st = cur.status
            changed = False
            if st.start_time is None:
                st.start_time = time.time()
                changed = True
            if (st.active, st.succeeded, st.failed) != (active, succeeded, failed):
                st.active, st.succeeded, st.failed = active, succeeded, failed
                changed = True
            if condition is not None and not any(
                c.type == condition[0] and c.status == "True"
                for c in st.conditions
            ):
                st.conditions.append(
                    v1.PodCondition(
                        type=condition[0], status="True", reason=condition[1]
                    )
                )
                if condition[0] == COND_COMPLETE:
                    st.completion_time = time.time()
                changed = True
            return cur if changed else None

        try:
            self.server.guaranteed_update(
                "jobs", job.metadata.namespace, job.metadata.name, mutate
            )
        except NotFound:
            pass
