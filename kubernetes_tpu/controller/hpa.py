"""HorizontalPodAutoscaler controller (autoscaling/v1 semantics).

Reference: pkg/controller/podautoscaler/horizontal.go (reconcileAutoscaler)
— every sync period, read the target's current CPU utilization from the
metrics API, compute

    desired = ceil(currentReplicas * currentUtilization / targetUtilization)

clamp to [minReplicas, maxReplicas], tolerate ±10% around the target
(the controller's `tolerance`), and write the scale subresource.

The reference reads utilization from metrics-server (an external
component); this build injects a ``metrics_client(pods) -> {pod_key:
millicores}`` callable. The default reads each pod's
``metrics.kubernetes.io/cpu-usage`` annotation (millicores) — hollow
runtimes and tests set it — which keeps the controller logic identical
while the metrics pipeline stays out-of-process, exactly like the
reference.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Callable, Dict, List, Optional

from ..api import objects as v1
from ..api.resources import cpu_to_millis
from ..client.apiserver import NotFound
from .base import WorkqueueController, match_labels

logger = logging.getLogger("kubernetes_tpu.controller.hpa")

CPU_USAGE_ANNOTATION = "metrics.kubernetes.io/cpu-usage"
TOLERANCE = 0.1  # horizontal.go tolerance
SCALE_TARGETS = {
    "Deployment": "deployments",
    "ReplicaSet": "replicasets",
    "StatefulSet": "statefulsets",
}


def annotation_metrics_client(pods: List[v1.Pod]) -> Dict[str, int]:
    """Default metrics source: per-pod cpu-usage annotation in millicores."""
    out = {}
    for p in pods:
        raw = p.metadata.annotations.get(CPU_USAGE_ANNOTATION)
        if raw is None:
            continue
        try:
            out[p.metadata.key] = cpu_to_millis(raw)
        except ValueError:
            pass
    return out


class HPAController(WorkqueueController):
    name = "horizontalpodautoscaling"
    primary_kind = "horizontalpodautoscalers"
    secondary_kinds = ()

    def __init__(
        self,
        server,
        workers: int = 1,
        sync_period: float = 5.0,
        metrics_client: Optional[Callable] = None,
    ):
        super().__init__(server, workers=workers)
        self.sync_period = sync_period
        self.metrics_client = metrics_client or annotation_metrics_client

    def start(self) -> None:
        super().start()
        # periodic re-evaluation (the reference reconciles every
        # --horizontal-pod-autoscaler-sync-period, default 15s)
        self.start_ticker("hpa-resync", self.sync_period, self._enqueue_all)

    def _enqueue_all(self) -> None:
        hpas, _ = self.server.list("horizontalpodautoscalers")
        for h in hpas:
            self.queue.add(h.metadata.key)

    # -- reconcile ------------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            hpa = self.server.get("horizontalpodautoscalers", ns, name)
        except NotFound:
            return
        resource = SCALE_TARGETS.get(hpa.spec.scale_target_ref.kind)
        if resource is None:
            logger.warning("hpa %s: unsupported target %s", key, hpa.spec.scale_target_ref.kind)
            return
        try:
            target = self.server.get(resource, ns, hpa.spec.scale_target_ref.name)
        except NotFound:
            return
        current = target.spec.replicas

        desired, utilization = self._desired_replicas(hpa, target, ns, current)
        desired = max(hpa.spec.min_replicas, min(hpa.spec.max_replicas, desired))

        if desired != current:
            def scale(obj):
                obj.spec.replicas = desired
                return obj

            try:
                self.server.guaranteed_update(
                    resource, ns, hpa.spec.scale_target_ref.name, scale
                )
            except NotFound:
                return

        def set_status(h):
            h.status.current_replicas = current
            h.status.desired_replicas = desired
            h.status.current_cpu_utilization_percentage = utilization
            if desired != current:
                h.status.last_scale_time = time.time()
            h.status.observed_generation = h.metadata.generation
            return h

        try:
            self.server.guaranteed_update(
                "horizontalpodautoscalers", ns, name, set_status
            )
        except NotFound:
            pass

    def _desired_replicas(self, hpa, target, ns: str, current: int):
        """(desired, currentUtilizationPct|None) — the v1 CPU-utilization
        rule with the ±tolerance dead band (horizontal.go
        computeReplicasForMetrics -> GetResourceReplicas)."""
        if hpa.spec.target_cpu_utilization_percentage is None or current == 0:
            return current, None
        pods = [
            p
            for p in self.server.list("pods", namespace=ns)[0]
            if p.metadata.deletion_timestamp is None
            and match_labels(target.spec.selector, p.metadata.labels)
        ]
        if not pods:
            return current, None
        usage = self.metrics_client(pods)
        measured = [p for p in pods if p.metadata.key in usage]
        if not measured:
            return current, None
        total_usage = sum(usage[p.metadata.key] for p in measured)
        total_request = 0
        for p in measured:
            req = v1.compute_pod_resource_request(p).get("cpu", 0)
            if req <= 0:
                return current, None  # missing requests: skip (reference errors)
            total_request += req
        utilization = int(round(100.0 * total_usage / total_request))
        target_pct = hpa.spec.target_cpu_utilization_percentage
        ratio = utilization / target_pct
        if abs(ratio - 1.0) <= TOLERANCE:
            return current, utilization
        return int(math.ceil(ratio * len(measured))), utilization
