"""Volume expansion controller.

Reference: pkg/controller/volume/expand/expand_controller.go — a PVC whose
requested size grows past its provisioned capacity triggers a resize,
gated on the StorageClass's allowVolumeExpansion. The reference splits the
work between a control-plane resize (PV capacity) and a node filesystem
resize (kubelet); this build's runtimes have no filesystems, so the
controller performs both halves: grow the bound PV's capacity, then
reflect it in pvc.status.capacity (the reference's markResizeFinished).
Shrinking is rejected by validation there and ignored here.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api import objects as v1
from ..api.resources import parse_quantity
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.volume_expand")


class VolumeExpandController(WorkqueueController):
    name = "persistentvolume-expander"
    primary_kind = "persistentvolumeclaims"
    secondary_kinds = ()

    def __init__(self, server, workers: int = 1):
        super().__init__(server, workers=workers)

    def _class_of(self, pvc) -> Optional[v1.StorageClass]:
        if not pvc.spec.storage_class_name:
            return None
        try:
            return self.server.get(
                "storageclasses", "", pvc.spec.storage_class_name
            )
        except NotFound:
            return None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            pvc = self.server.get("persistentvolumeclaims", ns, name)
        except NotFound:
            return
        if not pvc.spec.volume_name or pvc.status.phase != v1.CLAIM_BOUND:
            return  # only bound claims resize
        want = pvc.spec.resources.get("storage")
        if want is None:
            return
        have = pvc.status.capacity.get("storage")
        if have is None:
            # claim bound before status.capacity existed (older WAL):
            # baseline from the bound PV's provisioned size
            try:
                pv = self.server.get(
                    "persistentvolumes", "", pvc.spec.volume_name
                )
            except NotFound:
                return
            have = pv.spec.capacity.get("storage")
            if have is None:
                return
        if parse_quantity(want) <= parse_quantity(have):
            return
        sc = self._class_of(pvc)
        if sc is None or not sc.allow_volume_expansion:
            logger.info(
                "expand: PVC %s wants %s but class %r forbids expansion",
                key, want, pvc.spec.storage_class_name,
            )
            return

        # control-plane half: grow the PV
        def grow_pv(pv):
            cur = pv.spec.capacity.get("storage")
            if cur is not None and parse_quantity(cur) >= parse_quantity(want):
                return None
            pv.spec.capacity["storage"] = want
            return pv

        try:
            self.server.guaranteed_update(
                "persistentvolumes", "", pvc.spec.volume_name, grow_pv
            )
        except NotFound:
            return  # PV vanished; claim will be re-synced on events

        # "node" half: publish the new size on the claim status
        def finish(cur):
            h = cur.status.capacity.get("storage")
            if h is not None and parse_quantity(h) >= parse_quantity(want):
                return None
            cur.status.capacity["storage"] = want
            return cur

        try:
            self.server.guaranteed_update(
                "persistentvolumeclaims", ns, name, finish
            )
            logger.info("expand: PVC %s resized %s -> %s", key, have, want)
        except NotFound:
            pass
