"""Shared controller skeleton: watch loop → rate-limited workqueue → workers.

Every reference controller follows the same informer + workqueue + reconcile
shape (pkg/controller/replicaset/replica_set.go is the canonical example);
this base factors the thread plumbing so each controller is just its watch
wiring (`watch_kinds` / `enqueue_for_event`) and its reconcile (`sync`).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence

from ..api import objects as v1
from ..client.workqueue import RateLimitingQueue
from ..runtime.watch import BOOKMARK

logger = logging.getLogger("kubernetes_tpu.controller")


class WorkqueueController:
    """Subclasses set `name`, `primary_kind` (resource name whose objects'
    keys are the queue items) and implement `sync(key)`; override
    `enqueue_for_related(event_obj) -> key|None` per secondary kind."""

    name = "controller"
    primary_kind = ""
    # resource name -> method name to derive the primary key from an event
    secondary_kinds: Sequence[str] = ("pods",)

    def __init__(self, server, workers: int = 2):
        self.server = server
        self.queue = RateLimitingQueue()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.workers = workers

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(
            target=self._watch_loop, daemon=True, name=f"{self.name}-watch"
        )
        t.start()
        self._threads.append(t)
        for i in range(self.workers):
            w = threading.Thread(
                target=self._worker, daemon=True, name=f"{self.name}-worker-{i}"
            )
            w.start()
            self._threads.append(w)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()

    def start_ticker(self, name: str, period: float, fn) -> None:
        """Guarded periodic thread: time-driven controllers (expirations,
        resyncs, world sweeps) enqueue work on a clock, and ONE transient
        error must never kill the clock."""

        def loop():
            while not self._stop.wait(period):
                try:
                    fn()
                except Exception:
                    logger.exception("%s tick failed", name)

        t = threading.Thread(target=loop, daemon=True, name=name)
        t.start()
        self._threads.append(t)

    # -- event plumbing ------------------------------------------------------

    def primary_key_of(self, obj) -> str:
        """Queue key for a primary-kind event. Controllers whose sync
        rebuilds WORLD state (not per-object state) override this to a
        constant so the rate-limited queue collapses event bursts into one
        reconcile (the reference's desired-state-of-world populator)."""
        return obj.metadata.key

    def _watch_loop(self) -> None:
        from ..client.apiserver import list_and_watch

        def seed(objs):
            for o in objs:
                key = self.primary_key_of(o)
                if key:
                    self.queue.add(key)

        primary_watch = list_and_watch(self.server, self.primary_kind, seed)
        sec_watches = [
            (res, list_and_watch(self.server, res, lambda _objs: None))
            for res in self.secondary_kinds
        ]
        while not self._stop.is_set():
            # block briefly on the primary, then DRAIN all streams — one
            # event per tick would cap secondary throughput at ~5/s and
            # leave endpoints/PDB status minutes behind a pod burst
            ev = primary_watch.get(timeout=0.1)
            while ev is not None:
                # BOOKMARK = rv-only progress notify from the watch cache;
                # controllers track no resume position, so skip
                if ev.type != BOOKMARK:
                    key = self.primary_key_of(ev.object)
                    if key:
                        # falsy key = controller filtered the event out
                        self.queue.add(key)
                ev = primary_watch.get(timeout=0)
            for res, w in sec_watches:
                sev = w.get(timeout=0)
                while sev is not None:
                    if sev.type != BOOKMARK:
                        key = self.enqueue_for_related(res, sev.object)
                        if key:
                            self.queue.add(key)
                    sev = w.get(timeout=0)
        primary_watch.stop()
        for _, w in sec_watches:
            w.stop()

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        """Default: map a pod event to its controller owner of `owner_kind`."""
        owner = self.controller_owner(obj, self.owner_kind)
        if owner is not None:
            return f"{obj.metadata.namespace}/{owner.name}"
        return None

    owner_kind = ""  # e.g. "ReplicaSet" — used by the default enqueue

    @staticmethod
    def controller_owner(obj, kind: str) -> Optional[v1.OwnerReference]:
        return next(
            (
                r
                for r in obj.metadata.owner_references
                if r.controller and r.kind == kind
            ),
            None,
        )

    # -- reconcile plumbing --------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                self.sync(key)
                self.queue.forget(key)
            except Exception:
                logger.exception("%s: sync %s failed", self.name, key)
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    def sync(self, key: str) -> None:
        raise NotImplementedError

    # -- shared helpers ------------------------------------------------------

    def owned_pods(self, namespace: str, owner_kind: str, owner_name: str):
        pods, _ = self.server.list("pods", namespace=namespace)
        return [
            p
            for p in pods
            if p.metadata.deletion_timestamp is None
            and any(
                r.controller and r.kind == owner_kind and r.name == owner_name
                for r in p.metadata.owner_references
            )
        ]


from ..api.selectors import match_labels  # noqa: E402 — re-export for controllers


def pod_is_ready(pod: v1.Pod) -> bool:
    """podutil.IsPodReady: the Ready condition when the node agent posts
    one (readiness probes), else Running phase stands in (pods with no
    probe are Ready as soon as they run)."""
    if pod.status.phase != v1.POD_RUNNING:
        return False
    for c in pod.status.conditions:
        if c.type == v1.COND_POD_READY:
            return c.status == "True"
    return True
