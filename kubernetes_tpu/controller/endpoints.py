"""Endpoints controller: Service selector → backing pod addresses.

Reference: pkg/controller/endpoint/endpoints_controller.go (syncService) —
for every Service, the Endpoints object of the same name lists the IPs of
Running, IP-assigned pods matching the selector; pods not yet ready land in
notReadyAddresses. The proxy dataplane consumes these.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController, match_labels, pod_is_ready

logger = logging.getLogger("kubernetes_tpu.controller.endpoints")


class EndpointsController(WorkqueueController):
    name = "endpoints"
    primary_kind = "services"
    secondary_kinds = ("pods",)

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        # a pod event touches every service whose selector matches either
        # the old or new labels; re-list services in the pod's namespace
        svcs, _ = self.server.list("services", namespace=obj.metadata.namespace)
        for s in svcs:
            if s.spec.selector and match_labels(
                s.spec.selector, obj.metadata.labels
            ):
                self.queue.add(s.metadata.key)
        return None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            svc = self.server.get("services", ns, name)
        except NotFound:
            # service gone: remove its endpoints
            try:
                self.server.delete("endpoints", ns, name)
            except NotFound:
                pass
            return
        if not svc.spec.selector:
            return  # headless/manual endpoints are user-managed

        pods, _ = self.server.list("pods", namespace=ns)
        ready, not_ready = [], []
        for p in pods:
            if p.metadata.deletion_timestamp is not None:
                continue
            if not match_labels(svc.spec.selector, p.metadata.labels):
                continue
            if not p.spec.node_name:
                continue  # unscheduled pods have no address yet
            addr = v1.EndpointAddress(
                ip=p.status.pod_ip,
                node_name=p.spec.node_name,
                target_pod=p.metadata.key,
            )
            if pod_is_ready(p) and p.status.pod_ip:
                ready.append(addr)
            else:
                not_ready.append(addr)
        subset = v1.EndpointSubset(
            addresses=sorted(ready, key=lambda a: a.target_pod),
            not_ready_addresses=sorted(not_ready, key=lambda a: a.target_pod),
            ports=list(svc.spec.ports),
        )
        subsets = [subset] if (ready or not_ready) else []

        def mutate(cur):
            if cur.subsets == subsets:
                return None
            cur.subsets = subsets
            return cur

        try:
            self.server.guaranteed_update("endpoints", ns, name, mutate)
        except NotFound:
            ep = v1.Endpoints(
                metadata=v1.ObjectMeta(name=name, namespace=ns),
                subsets=subsets,
            )
            try:
                self.server.create("endpoints", ep)
            except AlreadyExists:
                pass
