"""Controller manager: run the reconcile loops under one leader election.

Reference: cmd/kube-controller-manager/app/controllermanager.go:372-414
(NewControllerInitializers) — each controller is started by name; disabled
controllers are skipped. A lost leader lease stops everything.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ..client.leaderelection import LeaderElectionConfig, LeaderElector
from .attachdetach import AttachDetachController
from .bootstrap import BootstrapSignerController
from .certificates import (
    CSRApprovingController,
    CSRCleanerController,
    CSRSigningController,
)
from .rbac import ClusterRoleAggregationController
from .volume_expand import VolumeExpandController
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .endpointslice import EndpointSliceController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoints import EndpointsController
from .garbagecollector import GarbageCollector
from .hpa import HPAController
from .job import JobController
from .namespace import NamespaceController
from .nodeipam import NodeIpamController
from .podgc import (
    PodGCController,
    PVCProtectionController,
    PVProtectionController,
    RootCACertPublisher,
)
from .nodelifecycle import NodeLifecycleController
from .pv_binder import PVBinderController
from .replicaset import ReplicaSetController, ReplicationControllerController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController, TokenCleaner
from .statefulset import StatefulSetController
from .ttl import TTLAfterFinishedController, TTLController

logger = logging.getLogger("kubernetes_tpu.controller.manager")

# reference list: cmd/kube-controller-manager/app/controllermanager.go:372-414
CONTROLLER_INITIALIZERS = {
    "replicaset": ReplicaSetController,
    "deployment": DeploymentController,
    "job": JobController,
    "daemonset": DaemonSetController,
    "statefulset": StatefulSetController,
    "endpoints": EndpointsController,
    "disruption": DisruptionController,
    "nodelifecycle": NodeLifecycleController,
    "garbagecollector": GarbageCollector,
    "namespace": NamespaceController,
    "horizontalpodautoscaling": HPAController,
    "cronjob": CronJobController,
    "resourcequota": ResourceQuotaController,
    "serviceaccount": ServiceAccountController,
    "ttl": TTLController,
    "ttlafterfinished": TTLAfterFinishedController,
    "endpointslice": EndpointSliceController,
    "nodeipam": NodeIpamController,
    "attachdetach": AttachDetachController,
    "persistentvolume-binder": PVBinderController,
    "podgc": PodGCController,
    "pvc-protection": PVCProtectionController,
    "pv-protection": PVProtectionController,
    "root-ca-cert-publisher": RootCACertPublisher,
    "replicationcontroller": ReplicationControllerController,
    "csrsigning": CSRSigningController,
    "csrapproving": CSRApprovingController,
    "csrcleaner": CSRCleanerController,
    "tokencleaner": TokenCleaner,
    "bootstrapsigner": BootstrapSignerController,
    "persistentvolume-expander": VolumeExpandController,
    "clusterrole-aggregation": ClusterRoleAggregationController,
}


class ControllerManager:
    def __init__(
        self,
        server,
        controllers: Optional[List[str]] = None,
        leader_election: Optional[LeaderElectionConfig] = None,
        watch_cache: bool = False,
        **controller_kwargs,
    ):
        self.server = server
        backend = server
        if watch_cache:
            # every controller's list+watch rides ONE shared Cacher: N
            # reconcile loops cost one store watch per kind instead of one
            # each (writes delegate through to the store untouched). The
            # elector below stays on the raw server — lease writes are a
            # fencing authority, never cache-served.
            from ..apiserver.cacher import Cacher

            backend = Cacher(server)
        self.backend = backend
        names = controllers or list(CONTROLLER_INITIALIZERS)
        self.controllers: Dict[str, object] = {}
        for name in names:
            init = CONTROLLER_INITIALIZERS.get(name)
            if init is None:
                raise ValueError(f"unknown controller {name!r}")
            kwargs = controller_kwargs.get(name, {})
            self.controllers[name] = init(backend, **kwargs)
        self._leader_cfg = leader_election
        self._elector = None
        self._started = threading.Event()

    def start(self) -> None:
        if self._leader_cfg is None:
            self._start_all()
            return

        def on_stopped():
            logger.error("controller-manager lost leadership; stopping")
            self.stop()

        self._elector = LeaderElector(
            self.server,
            self._leader_cfg,
            on_started_leading=self._start_all,
            on_stopped_leading=on_stopped,
        )
        threading.Thread(target=self._elector.run, daemon=True).start()

    def _start_all(self) -> None:
        for name, ctrl in self.controllers.items():
            ctrl.start()
            logger.info("started controller %s", name)
        self._started.set()

    def stop(self) -> None:
        for ctrl in self.controllers.values():
            ctrl.stop()
        if self._elector is not None:
            self._elector.stop()
        if self.backend is not self.server:
            # the Cacher this manager created: tear down its per-kind
            # store watches + bookmark thread with the controllers
            self.backend.stop()
