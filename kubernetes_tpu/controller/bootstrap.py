"""Bootstrap signer: JWS-signs the public cluster-info ConfigMap.

Reference: pkg/controller/bootstrap/bootstrapsigner.go — joining nodes
fetch `cluster-info` from kube-public WITHOUT credentials, so its
authenticity comes from detached JWS signatures keyed by bootstrap tokens:
for every signing-enabled token secret the controller stores
``jws-kubeconfig-<token-id>`` = sig(kubeconfig, token) in the ConfigMap,
and prunes signatures for deleted tokens. This build's signature is an
HMAC-SHA256 over the kubeconfig content keyed by ``<id>:<secret>``
(kubeadm-lite verifies the same construction on join) instead of a
JWS-serialized HS256 — same trust flow, simpler crypto.
"""

from __future__ import annotations

import hashlib
import hmac
import logging

from ..client.apiserver import Conflict, NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.bootstrap")

CLUSTER_INFO_NAMESPACE = "kube-public"
CLUSTER_INFO_NAME = "cluster-info"
KUBECONFIG_KEY = "kubeconfig"
JWS_PREFIX = "jws-kubeconfig-"
BOOTSTRAP_TOKEN_TYPE = "bootstrap.kubernetes.io/token"
TOKEN_ID_KEY = "token-id"
TOKEN_SECRET_KEY = "token-secret"
USAGE_SIGNING_KEY = "usage-bootstrap-signing"


def compute_detached_signature(content: str, token_id: str, token_secret: str) -> str:
    """The signature kubeadm-lite's join path verifies."""
    return hmac.new(
        f"{token_id}:{token_secret}".encode(), content.encode(), hashlib.sha256
    ).hexdigest()


def _as_str(v) -> str:
    return v.decode() if isinstance(v, bytes) else str(v)


class BootstrapSignerController(WorkqueueController):
    """World-state reconciler: any cluster-info or bootstrap-token event
    recomputes the full signature set (the reference enqueues a single
    constant key for the same reason)."""

    name = "bootstrapsigner"
    primary_kind = "configmaps"
    secondary_kinds = ("secrets",)

    WORLD = "__sign__"

    def __init__(self, server, workers: int = 1):
        super().__init__(server, workers=workers)

    def primary_key_of(self, obj) -> str:
        # only the one ConfigMap matters; collapse everything else
        if (
            obj.metadata.namespace == CLUSTER_INFO_NAMESPACE
            and obj.metadata.name == CLUSTER_INFO_NAME
        ):
            return self.WORLD
        return ""

    def enqueue_for_related(self, resource, obj):
        if getattr(obj, "type", "") == BOOTSTRAP_TOKEN_TYPE:
            return self.WORLD
        return None

    def _tokens(self):
        """{token-id: token-secret} for signing-enabled bootstrap tokens."""
        out = {}
        for s in self.server.list("secrets", namespace="kube-system")[0]:
            if s.type != BOOTSTRAP_TOKEN_TYPE:
                continue
            data = {**{k: _as_str(v) for k, v in s.data.items()}, **s.string_data}
            if data.get(USAGE_SIGNING_KEY, "").lower() != "true":
                continue
            tid, tsec = data.get(TOKEN_ID_KEY), data.get(TOKEN_SECRET_KEY)
            if tid and tsec:
                out[tid] = tsec
        return out

    def sync(self, key: str) -> None:
        if key != self.WORLD:
            return
        try:
            cm = self.server.get(
                "configmaps", CLUSTER_INFO_NAMESPACE, CLUSTER_INFO_NAME
            )
        except NotFound:
            return
        content = cm.data.get(KUBECONFIG_KEY)
        if content is None:
            return
        tokens = self._tokens()  # one secret list + HMAC set per reconcile
        old_sigs = {
            k[len(JWS_PREFIX):]: v
            for k, v in cm.data.items()
            if k.startswith(JWS_PREFIX)
        }
        new_sigs = {
            tid: compute_detached_signature(content, tid, tsec)
            for tid, tsec in tokens.items()
        }
        if new_sigs == old_sigs:
            return

        def mutate(cur):
            c = cur.data.get(KUBECONFIG_KEY)
            if c is None:
                return None
            data = {
                k: v for k, v in cur.data.items() if not k.startswith(JWS_PREFIX)
            }
            for tid, tsec in tokens.items():
                # re-sign over the re-read content (a conflict retry may
                # see a newer kubeconfig)
                data[JWS_PREFIX + tid] = compute_detached_signature(c, tid, tsec)
            if data == cur.data:
                return None
            cur.data = data
            return cur

        try:
            self.server.guaranteed_update(
                "configmaps", CLUSTER_INFO_NAMESPACE, CLUSTER_INFO_NAME, mutate
            )
        except (NotFound, Conflict):
            pass  # resync catches up
