"""DaemonSet controller: one pod per eligible node.

Reference: pkg/controller/daemon/daemon_controller.go (syncDaemonSet /
podsShouldBeOnNode). Eligibility = node matches the template's nodeSelector
and the pod's tolerations cover the node's NoSchedule/NoExecute taints.
Pods are created with a required node affinity match_fields term pinning
metadata.name to the target node, then flow through the normal scheduler —
the v1.18-era ScheduleDaemonSetPods path (the controller no longer sets
spec.nodeName itself).
"""

from __future__ import annotations

import copy
import logging
from typing import Dict, List, Optional

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController, pod_is_ready

logger = logging.getLogger("kubernetes_tpu.controller.daemonset")


def node_eligible(node: v1.Node, spec: v1.PodSpec) -> bool:
    """podsShouldBeOnNode's predicate subset: nodeSelector + taints."""
    for k, want in spec.node_selector.items():
        if node.metadata.labels.get(k) != want:
            return False
    taint = v1.find_untolerated_taint(node.spec.taints, spec.tolerations)
    return taint is None


class DaemonSetController(WorkqueueController):
    name = "daemonset"
    primary_kind = "daemonsets"
    secondary_kinds = ("pods", "nodes")
    owner_kind = "DaemonSet"

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        if resource == "nodes":
            # any node event can change eligibility for every DaemonSet
            dss, _ = self.server.list("daemonsets")
            for ds in dss:
                self.queue.add(ds.metadata.key)
            return None
        return super().enqueue_for_related(resource, obj)

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            ds = self.server.get("daemonsets", ns, name)
        except NotFound:
            return
        nodes, _ = self.server.list("nodes")
        pods = self.owned_pods(ns, "DaemonSet", name)
        by_node: Dict[str, List[v1.Pod]] = {}
        for p in pods:
            target = p.spec.node_name or _pinned_node(p)
            by_node.setdefault(target or "", []).append(p)

        eligible = {
            n.metadata.name for n in nodes if node_eligible(n, ds.spec.template.spec)
        }
        # create where missing
        for node_name in sorted(eligible):
            if not by_node.get(node_name):
                self._create_pod(ds, node_name)
        # remove where no longer eligible, plus duplicates
        misscheduled = 0
        for node_name, node_pods in by_node.items():
            if node_name and node_name not in eligible:
                misscheduled += len(node_pods)
                for p in node_pods:
                    self._delete_pod(p)
            else:
                for p in node_pods[1:]:
                    self._delete_pod(p)

        scheduled = sum(
            1 for n, ps in by_node.items() if n in eligible and ps
        )
        ready = sum(
            1
            for n, ps in by_node.items()
            if n in eligible and ps and pod_is_ready(ps[0])
        )

        def mutate(cur):
            st = cur.status
            new = (
                scheduled,
                len(eligible),
                ready,
                misscheduled,
                cur.metadata.generation,
            )
            old = (
                st.current_number_scheduled,
                st.desired_number_scheduled,
                st.number_ready,
                st.number_misscheduled,
                st.observed_generation,
            )
            if new == old:
                return None
            (
                st.current_number_scheduled,
                st.desired_number_scheduled,
                st.number_ready,
                st.number_misscheduled,
                st.observed_generation,
            ) = new
            return cur

        try:
            self.server.guaranteed_update("daemonsets", ns, name, mutate)
        except NotFound:
            pass

    def _create_pod(self, ds: v1.DaemonSet, node_name: str) -> None:
        tmpl = ds.spec.template
        spec = copy.deepcopy(tmpl.spec)
        # pin to the node via required affinity (ScheduleDaemonSetPods,
        # daemon_controller.go nodeAffinity replacement)
        pin = v1.NodeSelector(
            terms=(
                v1.NodeSelectorTerm(
                    match_fields=(
                        v1.NodeSelectorRequirement(
                            key="metadata.name", operator="In", values=(node_name,)
                        ),
                    )
                ),
            )
        )
        aff = spec.affinity or v1.Affinity()
        spec.affinity = v1.Affinity(
            node_affinity=v1.NodeAffinity(
                required=pin,
                preferred=(
                    aff.node_affinity.preferred if aff.node_affinity else ()
                ),
            ),
            pod_affinity=aff.pod_affinity,
            pod_anti_affinity=aff.pod_anti_affinity,
        )
        pod = v1.Pod(
            metadata=v1.ObjectMeta(
                name=f"{ds.metadata.name}-{node_name}",
                namespace=ds.metadata.namespace,
                labels=dict(tmpl.metadata.labels or ds.spec.selector),
                owner_references=[
                    v1.OwnerReference(
                        kind="DaemonSet",
                        name=ds.metadata.name,
                        uid=ds.metadata.uid,
                        controller=True,
                    )
                ],
            ),
            spec=spec,
        )
        try:
            self.server.create("pods", pod)
        except AlreadyExists:
            pass

    def _delete_pod(self, pod: v1.Pod) -> None:
        try:
            self.server.delete("pods", pod.metadata.namespace, pod.metadata.name)
        except NotFound:
            pass


def _pinned_node(pod: v1.Pod) -> Optional[str]:
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required:
        for term in aff.node_affinity.required.terms:
            for req in term.match_fields:
                if req.key == "metadata.name" and req.operator == "In" and req.values:
                    return req.values[0]
    return None
