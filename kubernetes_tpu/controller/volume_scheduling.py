"""Volume scheduling: the binder the scheduler consults for PVC-bearing pods.

Reference: pkg/controller/volume/scheduling/scheduler_binder.go
(`NewVolumeBinder`, FindPodVolumes/AssumePodVolumes/BindPodVolumes and the
PV assume cache), wired into the scheduler at pkg/scheduler/scheduler.go:
241-249 and consumed by the VolumeBinding plugin
(framework/plugins/volumebinding/volume_binding.go).

Same split as the reference:
  * Find — pure read: can this pod's claims be satisfied on this node?
    (bound claims → PV node affinity; unbound claims → a matching PV
    exists, or the class provisions dynamically)
  * Assume — optimistic in-memory claim→PV reservations for the chosen node
  * Bind — API writes (PV.claim_ref, PVC.volume_name/phase); a failed claim
    write rolls the already-written PV back, and the in-memory reservation
    is dropped either way

The "real" storage backend is the in-process API store; a FakeVolumeBinder
mirrors scheduler_binder_fake.go for tests and perf harnesses.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import objects as v1
from ..api.resources import parse_quantity
from ..client.apiserver import APIServer, NotFound

# node label keys a PV's zone constraint may use (volumezone.go)
ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)
REGION_LABELS = (
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
)


class ClaimNotFound(Exception):
    """Referenced PVC does not exist (UnschedulableAndUnresolvable class)."""


@dataclass
class PodVolumeDecision:
    """Planned bindings for one pod on one node (the assume-cache payload)."""

    static_bindings: List[Tuple[str, str]] = field(default_factory=list)
    # (claim key, pv name)
    dynamic_provisions: List[str] = field(default_factory=list)  # claim keys
    all_bound: bool = True


class VolumeBinder:
    """SchedulerVolumeBinder (scheduler_binder.go:NewVolumeBinder)."""

    def __init__(self, server: APIServer):
        self.server = server
        self._lock = threading.Lock()
        # claim key -> pv name reserved by an assumed (not yet bound) pod
        self._assumed_pv_for_claim: Dict[str, str] = {}
        # pod key -> decision awaiting bind
        self._decisions: Dict[str, PodVolumeDecision] = {}

    # -- lookups -------------------------------------------------------------

    def _claim(self, namespace: str, name: str) -> v1.PersistentVolumeClaim:
        try:
            return self.server.get("persistentvolumeclaims", namespace, name)
        except NotFound:
            raise ClaimNotFound(
                f"persistentvolumeclaim {namespace}/{name} not found"
            ) from None

    def _pv(self, name: str) -> Optional[v1.PersistentVolume]:
        try:
            return self.server.get("persistentvolumes", "", name)
        except NotFound:
            return None

    def _storage_class(self, name: Optional[str]) -> Optional[v1.StorageClass]:
        if not name:
            return None
        try:
            return self.server.get("storageclasses", "", name)
        except NotFound:
            return None

    def pod_claims(self, pod: v1.Pod) -> List[v1.PersistentVolumeClaim]:
        out = []
        for vol in pod.spec.volumes:
            if vol.persistent_volume_claim:
                out.append(
                    self._claim(pod.metadata.namespace, vol.persistent_volume_claim)
                )
        return out

    # -- find ----------------------------------------------------------------

    def find_pod_volumes(
        self, pod: v1.Pod, node: v1.Node
    ) -> Tuple[bool, bool, List[str]]:
        """(unbound_satisfied, bound_satisfied, reasons) —
        FindPodVolumes(scheduler_binder.go)."""
        reasons: List[str] = []
        unbound_ok = True
        bound_ok = True
        with self._lock:
            assumed = dict(self._assumed_pv_for_claim)
        taken = set(assumed.values())
        for claim in self.pod_claims(pod):
            key = claim.metadata.key
            pv_name = claim.spec.volume_name or assumed.get(key, "")
            if pv_name:
                pv = self._pv(pv_name)
                if pv is None or not self._pv_matches_node(pv, node):
                    bound_ok = False
                    reasons.append("node(s) had volume node affinity conflict")
                continue
            sc = self._storage_class(claim.spec.storage_class_name)
            if sc is not None and sc.volume_binding_mode == v1.BINDING_WAIT_FOR_FIRST_CONSUMER:
                # dynamic provisioning: satisfiable anywhere the provisioner
                # can reach; treated as satisfied (the fake PV controller /
                # provisioner completes it after bind)
                continue
            pv = self._find_matching_pv(claim, node, taken)
            if pv is None:
                unbound_ok = False
                reasons.append(
                    "node(s) didn't find available persistent volumes to bind"
                )
        return unbound_ok, bound_ok, reasons

    def _find_matching_pv(
        self,
        claim: v1.PersistentVolumeClaim,
        node: v1.Node,
        taken: set,
    ) -> Optional[v1.PersistentVolume]:
        want = parse_quantity(claim.spec.resources.get("storage", 0))
        pvs, _ = self.server.list("persistentvolumes")
        best = None
        best_cap = None
        for pv in pvs:
            if pv.metadata.name in taken or pv.spec.claim_ref:
                continue
            if (pv.spec.storage_class_name or "") != (
                claim.spec.storage_class_name or ""
            ):
                continue
            if claim.spec.access_modes and not set(claim.spec.access_modes) <= set(
                pv.spec.access_modes
            ):
                continue
            cap = parse_quantity(pv.spec.capacity.get("storage", 0))
            if cap < want:
                continue
            if not self._pv_matches_node(pv, node):
                continue
            # smallest PV that fits (volume.FindMatchingVolume semantics)
            if best is None or cap < best_cap:
                best, best_cap = pv, cap
        return best

    @staticmethod
    def _pv_matches_node(pv: v1.PersistentVolume, node: v1.Node) -> bool:
        na = pv.spec.node_affinity
        if na is None:
            return True
        from ..scheduler.framework.plugins.helpers import node_matches_term

        return any(node_matches_term(node, t) for t in na.terms)

    # -- assume --------------------------------------------------------------

    def assume_pod_volumes(self, pod: v1.Pod, node: v1.Node) -> bool:
        """Reserve claim→PV pairings in memory; returns all_bound
        (AssumePodVolumes)."""
        decision = PodVolumeDecision()
        with self._lock:
            taken = set(self._assumed_pv_for_claim.values())
        for claim in self.pod_claims(pod):
            key = claim.metadata.key
            if claim.spec.volume_name:
                continue
            sc = self._storage_class(claim.spec.storage_class_name)
            if sc is not None and sc.volume_binding_mode == v1.BINDING_WAIT_FOR_FIRST_CONSUMER:
                decision.dynamic_provisions.append(key)
                decision.all_bound = False
                continue
            pv = self._find_matching_pv(claim, node, taken)
            if pv is None:
                raise ValueError(
                    f"no persistent volume available for claim {key} on node "
                    f"{node.metadata.name} at assume time"
                )
            taken.add(pv.metadata.name)
            decision.static_bindings.append((key, pv.metadata.name))
            decision.all_bound = False
        with self._lock:
            for key, pv_name in decision.static_bindings:
                self._assumed_pv_for_claim[key] = pv_name
            if not decision.all_bound:
                self._decisions[pod.metadata.key] = decision
        return decision.all_bound

    def forget_pod_volumes(self, pod: v1.Pod) -> None:
        with self._lock:
            decision = self._decisions.pop(pod.metadata.key, None)
            if decision:
                for key, _ in decision.static_bindings:
                    self._assumed_pv_for_claim.pop(key, None)

    # -- bind ----------------------------------------------------------------

    def bind_pod_volumes(self, pod: v1.Pod, node_name: str = "") -> None:  # graftlint: degraded-ok(raise discipline: the scheduler binding cycle catches, unreserves and requeues the pod; the finally forgets volume decisions and the PV rollback below keeps bindings atomic)
        """Write the planned bindings to the API (BindPodVolumes)."""
        with self._lock:
            decision = self._decisions.get(pod.metadata.key)
        if decision is None:
            return
        try:
            for claim_key, pv_name in decision.static_bindings:
                ns, _, name = claim_key.partition("/")

                def bind_pv(p, _ck=claim_key):
                    p.spec.claim_ref = _ck
                    p.status.phase = "Bound"
                    return p

                def unbind_pv(p):
                    p.spec.claim_ref = None
                    p.status.phase = "Available"
                    return p

                try:
                    pv_capacity = self.server.get(
                        "persistentvolumes", "", pv_name
                    ).spec.capacity.get("storage")
                except NotFound:
                    pv_capacity = None

                def bind_claim(c, _pv=pv_name, _cap=pv_capacity):
                    c.spec.volume_name = _pv
                    c.status.phase = v1.CLAIM_BOUND
                    if _cap is not None:
                        # provisioned-size baseline for the expander
                        # (pv_binder._bind copies the same way)
                        c.status.capacity["storage"] = _cap
                    return c

                self.server.guaranteed_update("persistentvolumes", "", pv_name, bind_pv)
                try:
                    self.server.guaranteed_update(
                        "persistentvolumeclaims", ns, name, bind_claim
                    )
                except Exception:
                    # roll the PV back so it isn't orphaned-bound (claim_ref
                    # set, claim unbound) and unmatchable forever
                    try:
                        self.server.guaranteed_update(
                            "persistentvolumes", "", pv_name, unbind_pv
                        )
                    except NotFound:
                        pass
                    raise
            for claim_key in decision.dynamic_provisions:
                ns, _, name = claim_key.partition("/")

                def mark(c):
                    c.metadata.annotations[
                        "volume.kubernetes.io/selected-node"
                    ] = node_name
                    return c

                try:
                    self.server.guaranteed_update(
                        "persistentvolumeclaims", ns, name, mark
                    )
                except NotFound:
                    pass
        finally:
            self.forget_pod_volumes(pod)


class FakeVolumeBinder:
    """scheduler_binder_fake.go — configurable canned answers for tests."""

    def __init__(self, find=(True, True, []), assume_all_bound=True):
        self._find = find
        self._assume = assume_all_bound
        self.assume_called = False
        self.bind_called = False

    def pod_claims(self, pod):
        return []

    def find_pod_volumes(self, pod, node):
        return self._find

    def assume_pod_volumes(self, pod, node):
        self.assume_called = True
        return self._assume

    def forget_pod_volumes(self, pod):
        pass

    def bind_pod_volumes(self, pod, node_name=""):
        self.bind_called = True
