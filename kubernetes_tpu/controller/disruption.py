"""Disruption controller: keep PodDisruptionBudget status live.

Reference: pkg/controller/disruption/disruption.go (trySync/updatePdbStatus)
— for each PDB, count healthy matching pods, derive the desired healthy
count from minAvailable/maxUnavailable, and publish disruptionsAllowed.
The scheduler's preemption path reads disruptionsAllowed to prefer victims
whose eviction stays within budget (generic_scheduler.go:721
pickOneNodeForPreemption criterion #1, filterPodsWithPDBViolation).

expectedPods resolves through the pods' controller scale when available
(Deployment/ReplicaSet/StatefulSet spec.replicas), else falls back to the
matching-pod count — the reference's getExpectedScale behavior reduced to
the kinds this framework serves.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController, match_labels, pod_is_ready

logger = logging.getLogger("kubernetes_tpu.controller.disruption")

_SCALE_KINDS = {
    "ReplicaSet": "replicasets",
    "Deployment": "deployments",
    "StatefulSet": "statefulsets",
}


class DisruptionController(WorkqueueController):
    name = "disruption"
    primary_kind = "poddisruptionbudgets"
    secondary_kinds = ("pods",)

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        pdbs, _ = self.server.list(
            "poddisruptionbudgets", namespace=obj.metadata.namespace
        )
        for pdb in pdbs:
            if match_labels(pdb.spec.selector, obj.metadata.labels):
                self.queue.add(pdb.metadata.key)
        return None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            pdb = self.server.get("poddisruptionbudgets", ns, name)
        except NotFound:
            return
        pods, _ = self.server.list("pods", namespace=ns)
        matching = [
            p
            for p in pods
            if p.metadata.deletion_timestamp is None
            and match_labels(pdb.spec.selector, p.metadata.labels)
        ]
        healthy = sum(1 for p in matching if pod_is_ready(p))
        expected = self._expected_scale(matching) or len(matching)

        if pdb.spec.min_available is not None:
            desired = min(pdb.spec.min_available, expected)
        elif pdb.spec.max_unavailable is not None:
            desired = max(0, expected - pdb.spec.max_unavailable)
        else:
            desired = expected  # no budget field: nothing may be disrupted
        allowed = max(0, healthy - desired)

        def mutate(cur):
            st = cur.status
            new = (allowed, healthy, desired, expected, cur.metadata.generation)
            old = (
                st.disruptions_allowed,
                st.current_healthy,
                st.desired_healthy,
                st.expected_pods,
                st.observed_generation,
            )
            if new == old:
                return None
            (
                st.disruptions_allowed,
                st.current_healthy,
                st.desired_healthy,
                st.expected_pods,
                st.observed_generation,
            ) = new
            return cur

        try:
            self.server.guaranteed_update("poddisruptionbudgets", ns, name, mutate)
        except NotFound:
            pass

    def _expected_scale(self, pods: List[v1.Pod]) -> int:
        total = 0
        seen = set()
        for p in pods:
            ref = next(
                (r for r in p.metadata.owner_references if r.controller), None
            )
            if ref is None:
                total += 1
                continue
            k = (ref.kind, ref.name)
            if k in seen:
                continue
            seen.add(k)
            resource = _SCALE_KINDS.get(ref.kind)
            if resource is None:
                total += 1
                continue
            try:
                owner = self.server.get(
                    resource, p.metadata.namespace, ref.name
                )
                total += owner.spec.replicas
            except NotFound:
                total += 1
        return total
