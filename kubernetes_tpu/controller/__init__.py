"""Controller components (reference pkg/controller/...).

Currently: volume scheduling (the PV binder the scheduler shares with the
PV controller, reference pkg/controller/volume/scheduling/).
"""
