"""CronJob controller: spawn Jobs on a cron schedule.

Reference: pkg/controller/cronjob/cronjob_controller.go (syncOne +
utils.go getRecentUnmetScheduleTimes) — every sync period, for each
CronJob: find the most recent unmet schedule time; if it is within
startingDeadlineSeconds, create a Job named ``<cronjob>-<scheduled unix
minute>`` (idempotent: the deterministic name makes double-creates
AlreadyExists no-ops); apply the concurrency policy (Allow | Forbid |
Replace); prune finished Jobs beyond the history limits.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from typing import List, Optional

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from ..utils.cron import CronSchedule
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.cronjob")


def _job_is_finished(job: v1.Job) -> bool:
    return any(
        c.type in ("Complete", "Failed") and c.status == "True"
        for c in job.status.conditions
    )


class CronJobController(WorkqueueController):
    name = "cronjob"
    primary_kind = "cronjobs"
    secondary_kinds = ("jobs",)
    owner_kind = "CronJob"

    def __init__(self, server, workers: int = 1, sync_period: float = 2.0):
        super().__init__(server, workers=workers)
        self.sync_period = sync_period

    def start(self) -> None:
        super().start()
        # the reference controller re-lists every 10s (syncAll); schedules
        # fire from this tick, not from watch events
        self.start_ticker("cronjob-tick", self.sync_period, self._enqueue_all)

    def _enqueue_all(self) -> None:
        cjs, _ = self.server.list("cronjobs")
        for cj in cjs:
            self.queue.add(cj.metadata.key)

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            cj = self.server.get("cronjobs", ns, name)
        except NotFound:
            return
        jobs, _ = self.server.list("jobs", namespace=ns)
        owned = [
            j
            for j in jobs
            if any(
                r.controller and r.kind == "CronJob" and r.name == name
                for r in j.metadata.owner_references
            )
        ]
        active = [j for j in owned if not _job_is_finished(j)]
        self._update_active_status(ns, name, [j.metadata.key for j in active])
        self._prune_history(cj, owned)
        if cj.spec.suspend:
            return

        now = time.time()
        sched = CronSchedule(cj.spec.schedule)
        # most recent unmet time after the last handled schedule; creation
        # time anchors the first window
        anchor = cj.status.last_schedule_time or cj.metadata.creation_timestamp or now
        try:
            next_t = sched.next_after(anchor)
        except ValueError:
            logger.warning("cronjob %s: unsatisfiable schedule %r", key, cj.spec.schedule)
            return
        if next_t > now:
            return  # nothing due yet
        # walk to the LAST unmet time <= now (missed runs collapse into one,
        # like the reference when too many schedules are missed)
        scheduled_t = next_t
        while True:
            nxt = sched.next_after(scheduled_t)
            if nxt > now:
                break
            scheduled_t = nxt
        if (
            cj.spec.starting_deadline_seconds is not None
            and now - scheduled_t > cj.spec.starting_deadline_seconds
        ):
            self._bump_last_schedule(ns, name, scheduled_t)
            return  # missed the starting deadline: skip this run

        if active:
            if cj.spec.concurrency_policy == v1_FORBID:
                # do NOT bump last_schedule_time: the missed run starts once
                # the active job finishes (subject to startingDeadline) —
                # bumping here would drop it permanently (syncOne semantics)
                return
            if cj.spec.concurrency_policy == v1_REPLACE:
                for j in active:
                    try:
                        self.server.delete("jobs", ns, j.metadata.name)
                    except NotFound:
                        pass

        job = self._job_for(cj, scheduled_t)
        try:
            self.server.create("jobs", job)
        except AlreadyExists:
            pass  # deterministic name: this run already fired
        self._bump_last_schedule(ns, name, scheduled_t)

    # -- helpers --------------------------------------------------------------

    def _job_for(self, cj: v1.CronJob, scheduled_t: float) -> v1.Job:
        tpl = cj.spec.job_template
        job = v1.Job(
            metadata=v1.ObjectMeta(
                name=f"{cj.metadata.name}-{int(scheduled_t // 60)}",
                namespace=cj.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations=dict(tpl.metadata.annotations),
                owner_references=[
                    v1.OwnerReference(
                        kind="CronJob",
                        name=cj.metadata.name,
                        uid=cj.metadata.uid,
                        controller=True,
                    )
                ],
            ),
            spec=copy.deepcopy(tpl.spec),
        )
        return job

    def _prune_history(self, cj: v1.CronJob, owned: List[v1.Job]) -> None:
        for cond, limit in (
            ("Complete", cj.spec.successful_jobs_history_limit),
            ("Failed", cj.spec.failed_jobs_history_limit),
        ):
            finished = sorted(
                (
                    j
                    for j in owned
                    if any(
                        c.type == cond and c.status == "True"
                        for c in j.status.conditions
                    )
                ),
                key=lambda j: j.metadata.creation_timestamp or 0.0,
            )
            for j in finished[: max(0, len(finished) - limit)]:
                try:
                    self.server.delete("jobs", j.metadata.namespace, j.metadata.name)
                except NotFound:
                    pass

    def _bump_last_schedule(self, ns: str, name: str, t: float) -> None:
        def mutate(cur):
            if (cur.status.last_schedule_time or 0) >= t:
                return None
            cur.status.last_schedule_time = t
            return cur

        try:
            self.server.guaranteed_update("cronjobs", ns, name, mutate)
        except NotFound:
            pass

    def _update_active_status(self, ns: str, name: str, active_keys: List[str]) -> None:
        def mutate(cur):
            if cur.status.active == active_keys:
                return None
            cur.status.active = active_keys
            return cur

        try:
            self.server.guaranteed_update("cronjobs", ns, name, mutate)
        except NotFound:
            pass


v1_FORBID = "Forbid"
v1_REPLACE = "Replace"
