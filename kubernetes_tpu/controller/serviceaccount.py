"""ServiceAccount + token controllers.

Reference: pkg/controller/serviceaccount/serviceaccounts_controller.go —
ensure every (non-terminating) namespace has the "default" ServiceAccount —
and tokens_controller.go — ensure every ServiceAccount has a token Secret
(type kubernetes.io/service-account-token) referenced from its
``secrets`` list; deleting the SA deletes its tokens.
"""

from __future__ import annotations

import logging
import secrets as _secrets
from typing import Optional

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.serviceaccount")

TOKEN_SECRET_TYPE = "kubernetes.io/service-account-token"
SA_ANNOTATION = "kubernetes.io/service-account.name"


class ServiceAccountController(WorkqueueController):
    """Namespaces are the primary: each sync ensures default SA + token."""

    name = "serviceaccount"
    primary_kind = "namespaces"
    secondary_kinds = ("serviceaccounts",)

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        # SA deleted/changed -> re-sync its namespace. Namespace objects sit
        # in the store under the default namespace (their metadata.namespace
        # is not themselves), so reconstruct that store key.
        ns = obj.metadata.namespace
        if not ns:
            return None
        for cand in self.server.list("namespaces")[0]:
            if cand.metadata.name == ns:
                return cand.metadata.key
        return None

    def sync(self, key: str) -> None:
        store_ns, _, name = key.rpartition("/")
        try:
            ns_obj = self.server.get("namespaces", store_ns, name)
        except NotFound:
            return
        if ns_obj.metadata.deletion_timestamp is not None:
            return
        # ensure the default ServiceAccount
        try:
            sa = self.server.get("serviceaccounts", name, "default")
        except NotFound:
            sa = v1.ServiceAccount(
                metadata=v1.ObjectMeta(name="default", namespace=name)
            )
            try:
                sa = self.server.create("serviceaccounts", sa)
            except AlreadyExists:
                sa = self.server.get("serviceaccounts", name, "default")
        self._ensure_token(sa)
        self._gc_orphaned_tokens(name)

    def _gc_orphaned_tokens(self, namespace: str) -> None:
        """Token secrets whose ServiceAccount is gone must be DELETED —
        otherwise the bearer credential keeps authenticating a revoked
        identity (tokens_controller deletes on SA deletion)."""
        sas = {
            sa.metadata.name
            for sa in self.server.list("serviceaccounts", namespace=namespace)[0]
        }
        for s in self.server.list("secrets", namespace=namespace)[0]:
            if s.type != TOKEN_SECRET_TYPE:
                continue
            owner = s.metadata.annotations.get(SA_ANNOTATION, "")
            if owner and owner not in sas:
                try:
                    self.server.delete("secrets", namespace, s.metadata.name)
                except NotFound:
                    pass

    def _ensure_token(self, sa: v1.ServiceAccount) -> None:
        """tokens_controller.go ensureReferencedToken: a token Secret exists
        and is referenced from sa.secrets."""
        ns = sa.metadata.namespace
        token_name = f"{sa.metadata.name}-token"
        try:
            self.server.get("secrets", ns, token_name)
        except NotFound:
            secret = v1.Secret(
                metadata=v1.ObjectMeta(
                    name=token_name,
                    namespace=ns,
                    annotations={SA_ANNOTATION: sa.metadata.name},
                ),
                type=TOKEN_SECRET_TYPE,
                data={"token": _secrets.token_urlsafe(24).encode()},
            )
            try:
                self.server.create("secrets", secret)
            except AlreadyExists:
                pass
        if token_name not in sa.secrets:
            def mutate(cur):
                if token_name in cur.secrets:
                    return None
                cur.secrets.append(token_name)
                return cur

            try:
                self.server.guaranteed_update(
                    "serviceaccounts", ns, sa.metadata.name, mutate
                )
            except NotFound:
                pass


class TokenCleaner(WorkqueueController):
    """Delete expired bootstrap token secrets
    (pkg/controller/bootstrap/tokencleaner.go): secrets of type
    ``bootstrap.kubernetes.io/token`` carry an ``expiration`` annotation
    (unix seconds); past it, the join credential is revoked."""

    name = "tokencleaner"
    primary_kind = "secrets"
    secondary_kinds = ()

    EXPIRATION_ANNOTATION = "expiration"
    BOOTSTRAP_TYPE = "bootstrap.kubernetes.io/token"

    def __init__(self, server, workers: int = 1, tick: float = 5.0):
        super().__init__(server, workers=workers)
        self.tick = tick

    def start(self) -> None:
        super().start()
        # expirations fire by time, not by watch events. Bootstrap tokens
        # live in kube-system only, and only expiring ones need ticks — the
        # cleaner must not deep-copy every secret in the cluster each tick.
        self.start_ticker("tokencleaner-tick", self.tick, self._enqueue_expiring)

    def _enqueue_expiring(self) -> None:
        for s in self.server.list("secrets", namespace="kube-system")[0]:
            if (
                s.type == self.BOOTSTRAP_TYPE
                and self.EXPIRATION_ANNOTATION in s.metadata.annotations
            ):
                self.queue.add(s.metadata.key)

    def sync(self, key: str) -> None:
        import time as _time

        ns, _, name = key.rpartition("/")
        try:
            secret = self.server.get("secrets", ns, name)
        except NotFound:
            return
        if secret.type != self.BOOTSTRAP_TYPE:
            return
        raw = secret.metadata.annotations.get(self.EXPIRATION_ANNOTATION)
        if raw is None:
            return  # non-expiring token
        try:
            expires = float(raw)
        except ValueError:
            logger.warning("token %s: bad expiration %r; deleting", key, raw)
            expires = 0.0
        if _time.time() >= expires:
            try:
                self.server.delete("secrets", ns, name)
                logger.info("expired bootstrap token %s deleted", key)
            except NotFound:
                pass
