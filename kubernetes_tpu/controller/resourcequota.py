"""ResourceQuota controller: track per-namespace usage against hard limits.

Reference: pkg/controller/resourcequota/resource_quota_controller.go —
recalculates ``status.used`` for every quota whenever objects it tracks
change (pods by default here: pod count, requests.cpu, requests.memory),
plus a full resync. ENFORCEMENT is the quota admission plugin's job
(apiserver/admission.py); this controller only keeps status current — the
same split as the reference (controller = accounting, admission = gate).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict

from ..api import objects as v1
from ..api.resources import CPU, MEMORY
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.resourcequota")

# quota resource names we account (reference: evaluator core.Pod)
Q_PODS = "pods"
Q_REQ_CPU = "requests.cpu"
Q_REQ_MEM = "requests.memory"
Q_CPU = "cpu"  # alias of requests.cpu (v1 compatibility)
Q_MEM = "memory"


def compute_namespace_usage(
    server, namespace: str, scopes=()
) -> Dict[str, int]:
    """Usage for one namespace, restricted to pods matching `scopes`
    (reference quota scope selection, evaluator/core/pods.go). Terminal
    pods don't count (the evaluator skips Succeeded/Failed pods)."""
    from ..apiserver.admission import pod_matches_scopes

    pods, _ = server.list("pods", namespace=namespace)
    live = [
        p
        for p in pods
        if p.metadata.deletion_timestamp is None
        and p.status.phase not in (v1.POD_SUCCEEDED, v1.POD_FAILED)
        and (not scopes or pod_matches_scopes(p, scopes))
    ]
    cpu = mem = 0
    for p in live:
        req = v1.compute_pod_resource_request(p)
        cpu += int(req.get(CPU, 0))
        mem += int(req.get(MEMORY, 0))
    return {
        Q_PODS: len(live),
        Q_REQ_CPU: cpu,
        Q_CPU: cpu,
        Q_REQ_MEM: mem,
        Q_MEM: mem,
    }


class ResourceQuotaController(WorkqueueController):
    name = "resourcequota"
    primary_kind = "resourcequotas"
    secondary_kinds = ("pods",)

    def __init__(self, server, workers: int = 1, resync_period: float = 10.0):
        super().__init__(server, workers=workers)
        self.resync_period = resync_period

    def start(self) -> None:
        super().start()
        self.start_ticker("quota-resync", self.resync_period, self._enqueue_all)

    def _enqueue_all(self) -> None:
        quotas, _ = self.server.list("resourcequotas")
        for q in quotas:
            self.queue.add(q.metadata.key)

    def enqueue_for_related(self, resource: str, obj):
        # a pod event re-syncs every quota in its namespace
        quotas, _ = self.server.list(
            "resourcequotas", namespace=obj.metadata.namespace
        )
        for q in quotas:
            self.queue.add(q.metadata.key)
        return None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            quota = self.server.get("resourcequotas", ns, name)
        except NotFound:
            return
        usage = compute_namespace_usage(self.server, ns, quota.spec.scopes)
        used = {r: usage.get(r, 0) for r in quota.spec.hard}

        def mutate(cur):
            if cur.status.used == used and cur.status.hard == cur.spec.hard:
                return None
            cur.status.hard = dict(cur.spec.hard)
            cur.status.used = used
            return cur

        try:
            self.server.guaranteed_update("resourcequotas", ns, name, mutate)
        except NotFound:
            pass
