"""Attach/detach controller: VolumeAttachment objects follow pod placement.

Reference: pkg/controller/volume/attachdetach — reconciles the desired
state (pods scheduled to nodes referencing PV-backed volumes) against the
actual state (VolumeAttachment objects): attach volumes whose pods landed
on a node, detach when no pod on that node uses the volume anymore.
Attachment names are deterministic hashes of (pv, node) — like the
reference's GetAttachmentName — so reconcile is idempotent and distinct
pairs can't collide. The hollow runtime "attaches" instantly
(status.attached) the way kubemark fakes the mounter.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Dict, Optional, Set, Tuple

from ..api import objects as v1
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.attachdetach")


def _pod_pv_names(server, pod: v1.Pod) -> Set[str]:
    """PVs referenced by the pod via bound PVCs."""
    out: Set[str] = set()
    for vol in pod.spec.volumes:
        if not vol.persistent_volume_claim:
            continue
        try:
            pvc = server.get(
                "persistentvolumeclaims",
                pod.metadata.namespace,
                vol.persistent_volume_claim,
            )
        except NotFound:
            continue
        if pvc.spec.volume_name:
            out.add(pvc.spec.volume_name)
    return out


class AttachDetachController(WorkqueueController):
    name = "attachdetach"
    primary_kind = "pods"
    # nodes: a volumes_in_use drop (kubelet unmounted) must retry the
    # delayed safe detach
    secondary_kinds = ("persistentvolumeclaims", "nodes")

    def primary_key_of(self, obj) -> str:
        # sync() rebuilds the whole desired-state-of-world; a constant key
        # lets the workqueue collapse a pod burst into ONE rebuild instead
        # of N full-cluster scans
        return "reconcile"

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        return "reconcile"  # PVC binding changes: same world rebuild

    def sync(self, key: str) -> None:
        # desired state of the WORLD, not of one pod: rebuild the full
        # (pv, node) -> wanted map like the reference's reconciler loop —
        # per-pod increments can't handle detach-on-delete (the pod is gone)
        pods, _ = self.server.list("pods")
        wanted: Dict[Tuple[str, str], bool] = {}
        for p in pods:
            if not p.spec.node_name or p.metadata.deletion_timestamp is not None:
                continue
            for pv_name in _pod_pv_names(self.server, p):
                wanted[(pv_name, p.spec.node_name)] = True

        attachments, _ = self.server.list("volumeattachments")
        have = {(a.spec.pv_name, a.spec.node_name): a for a in attachments}

        for (pv_name, node_name) in wanted:
            if (pv_name, node_name) in have:
                continue
            # hashed name (GetAttachmentName): "pv-a"+"b" vs "pv"+"a-b"
            # must not collide
            digest = hashlib.sha1(
                f"{pv_name}^{node_name}".encode()
            ).hexdigest()[:20]
            va = v1.VolumeAttachment(
                metadata=v1.ObjectMeta(name=f"va-{digest}", namespace=""),
                spec=v1.VolumeAttachmentSpec(
                    attacher=self._attacher_of(pv_name),
                    node_name=node_name,
                    pv_name=pv_name,
                ),
                status=v1.VolumeAttachmentStatus(attached=True),
            )
            try:
                self.server.create("volumeattachments", va)
            except AlreadyExists:
                pass
        for (pv_name, node_name), a in have.items():
            if (pv_name, node_name) not in wanted:
                # safe detach: never while the node still reports the
                # volume in use (volumes_in_use, the kubelet volume
                # manager's mount bookkeeping — reconciler.go's
                # "operation not permitted while mounted" contract)
                if pv_name in self._volumes_in_use(node_name):
                    logger.info(
                        "delaying detach of %s from %s: still in use",
                        pv_name,
                        node_name,
                    )
                    continue
                try:
                    self.server.delete(
                        "volumeattachments",
                        a.metadata.namespace,
                        a.metadata.name,
                    )
                except NotFound:
                    pass

    def _volumes_in_use(self, node_name: str) -> Set[str]:
        try:
            node = self.server.get("nodes", "", node_name)
        except NotFound:
            try:
                node = self.server.get("nodes", "default", node_name)
            except NotFound:
                return set()
        return set(node.status.volumes_in_use)

    def _attacher_of(self, pv_name: str) -> str:
        try:
            pv = self.server.get("persistentvolumes", "", pv_name)
        except NotFound:
            try:
                pv = self.server.get("persistentvolumes", "default", pv_name)
            except NotFound:
                return ""
        s = pv.spec
        if s.csi:
            return s.csi.driver
        for attr, drv in (
            ("gce_persistent_disk", "kubernetes.io/gce-pd"),
            ("aws_elastic_block_store", "kubernetes.io/aws-ebs"),
            ("azure_disk", "kubernetes.io/azure-disk"),
            ("cinder", "kubernetes.io/cinder"),
        ):
            if getattr(s, attr, None):
                return drv
        return "kubernetes.io/no-op"
