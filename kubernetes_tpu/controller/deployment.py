"""Deployment controller: template-hashed ReplicaSet chain + rolling update.

Reference: pkg/controller/deployment/deployment_controller.go (syncDeployment)
and rolling.go (reconcileNewReplicaSet / reconcileOldReplicaSets). A
Deployment owns one ReplicaSet per distinct pod template (identified by a
stable hash); rollout scales the new RS up within spec.replicas + maxSurge
and the old RSs down while keeping availability above
spec.replicas − maxUnavailable. Surge/unavailable are absolute counts here
(the reference also accepts percentages — intentional simplification).
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
from typing import List, Optional, Tuple

from ..api import objects as v1
from ..api.serialization import to_dict
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController, pod_is_ready

logger = logging.getLogger("kubernetes_tpu.controller.deployment")


def template_hash(tmpl: v1.PodTemplateSpec) -> str:
    """Stable short hash of a pod template (pod-template-hash label value;
    reference controller.ComputeHash)."""
    d = to_dict(tmpl)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:10]


class DeploymentController(WorkqueueController):
    name = "deployment"
    primary_kind = "deployments"
    secondary_kinds = ("replicasets",)
    owner_kind = "Deployment"

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            dep = self.server.get("deployments", ns, name)
        except NotFound:
            return  # GC cascades to RSs / pods
        if dep.spec.paused:
            return

        new_rs, old_rss = self._get_replica_sets(dep)
        if new_rs is None:
            new_rs = self._create_replica_set(dep)
            if new_rs is None:
                return
        if dep.spec.strategy.type == v1.RECREATE:
            self._rollout_recreate(dep, new_rs, old_rss)
        else:
            self._rollout_rolling(dep, new_rs, old_rss)
        self._sync_status(dep, new_rs, old_rss)

    # -- replica set management ---------------------------------------------

    def _get_replica_sets(
        self, dep: v1.Deployment
    ) -> Tuple[Optional[v1.ReplicaSet], List[v1.ReplicaSet]]:
        want_hash = template_hash(dep.spec.template)
        rss, _ = self.server.list("replicasets", namespace=dep.metadata.namespace)
        mine = [
            rs
            for rs in rss
            if any(
                r.controller and r.kind == "Deployment" and r.name == dep.metadata.name
                for r in rs.metadata.owner_references
            )
        ]
        new = next(
            (
                rs
                for rs in mine
                if rs.metadata.labels.get("pod-template-hash") == want_hash
            ),
            None,
        )
        old = [rs for rs in mine if rs is not new]
        return new, old

    def _create_replica_set(self, dep: v1.Deployment) -> Optional[v1.ReplicaSet]:
        h = template_hash(dep.spec.template)
        tmpl = copy.deepcopy(dep.spec.template)
        tmpl.metadata.labels = dict(tmpl.metadata.labels or dep.spec.selector)
        tmpl.metadata.labels["pod-template-hash"] = h
        rs = v1.ReplicaSet(
            metadata=v1.ObjectMeta(
                name=f"{dep.metadata.name}-{h}",
                namespace=dep.metadata.namespace,
                labels={**dep.spec.selector, "pod-template-hash": h},
                owner_references=[
                    v1.OwnerReference(
                        kind="Deployment",
                        name=dep.metadata.name,
                        uid=dep.metadata.uid,
                        controller=True,
                    )
                ],
            ),
            spec=v1.ReplicaSetSpec(
                replicas=0,
                selector={**dep.spec.selector, "pod-template-hash": h},
                template=tmpl,
            ),
        )
        try:
            return self.server.create("replicasets", rs)
        except AlreadyExists:
            try:
                return self.server.get(
                    "replicasets", rs.metadata.namespace, rs.metadata.name
                )
            except NotFound:
                return None

    def _scale_rs(self, rs: v1.ReplicaSet, replicas: int) -> None:
        if rs.spec.replicas == replicas:
            return

        def mutate(cur):
            if cur.spec.replicas == replicas:
                return None
            cur.spec.replicas = replicas
            return cur

        try:
            self.server.guaranteed_update(
                "replicasets", rs.metadata.namespace, rs.metadata.name, mutate
            )
        except NotFound:
            pass

    # -- rollout strategies ---------------------------------------------------

    def _ready_by_rs(self, dep: v1.Deployment) -> dict:
        """One pod listing per sync, partitioned by owning ReplicaSet name
        (the reference controller works from informer-indexed pod lists)."""
        pods, _ = self.server.list("pods", namespace=dep.metadata.namespace)
        out: dict = {}
        for p in pods:
            if p.metadata.deletion_timestamp is not None:
                continue
            ref = self.controller_owner(p, "ReplicaSet")
            if ref is not None and pod_is_ready(p):
                out[ref.name] = out.get(ref.name, 0) + 1
        return out

    def _rollout_rolling(
        self, dep: v1.Deployment, new_rs: v1.ReplicaSet, old_rss: List[v1.ReplicaSet]
    ) -> None:
        want = dep.spec.replicas
        surge = dep.spec.strategy.max_surge
        max_unavail = dep.spec.strategy.max_unavailable
        old_total = sum(rs.spec.replicas for rs in old_rss)

        # reconcileNewReplicaSet: scale new up to want, bounded by
        # want + surge total pods across all RSs; scale DOWN when the
        # deployment itself shrank (new RS above want with no rollout going)
        new_target = min(want, max(0, want + surge - old_total))
        if new_target > new_rs.spec.replicas or new_rs.spec.replicas > want:
            self._scale_rs(new_rs, new_target if new_target > new_rs.spec.replicas else want)

        # reconcileOldReplicaSets: scale old down as readiness allows
        ready_by_rs = self._ready_by_rs(dep)
        ready = ready_by_rs.get(new_rs.metadata.name, 0) + sum(
            ready_by_rs.get(rs.metadata.name, 0) for rs in old_rss
        )
        min_available = want - max_unavail
        can_remove = max(0, ready - min_available)
        # also remove pods beyond the surge budget regardless of readiness
        total = new_rs.spec.replicas + old_total
        can_remove = max(can_remove, total - (want + surge))
        for rs in sorted(old_rss, key=lambda r: r.metadata.creation_timestamp):
            if can_remove <= 0:
                break
            drop = min(rs.spec.replicas, can_remove)
            if drop > 0:
                self._scale_rs(rs, rs.spec.replicas - drop)
                can_remove -= drop

        self._cleanup_old(dep, old_rss)

    def _rollout_recreate(
        self, dep: v1.Deployment, new_rs: v1.ReplicaSet, old_rss: List[v1.ReplicaSet]
    ) -> None:
        # scale all old to zero first; only then bring up the new template
        for rs in old_rss:
            if rs.spec.replicas:
                self._scale_rs(rs, 0)
        old_pods = [
            p
            for rs in old_rss
            for p in self.owned_pods(
                rs.metadata.namespace, "ReplicaSet", rs.metadata.name
            )
        ]
        if not old_pods:
            self._scale_rs(new_rs, dep.spec.replicas)
        self._cleanup_old(dep, old_rss)

    def _cleanup_old(self, dep: v1.Deployment, old_rss: List[v1.ReplicaSet]) -> None:
        """revisionHistoryLimit: drop empty old RSs beyond the limit."""
        empties = [
            rs
            for rs in old_rss
            if rs.spec.replicas == 0 and rs.status.replicas == 0
        ]
        excess = len(empties) - dep.spec.revision_history_limit
        if excess <= 0:
            return
        empties.sort(key=lambda r: r.metadata.creation_timestamp)
        for rs in empties[:excess]:
            try:
                self.server.delete(
                    "replicasets", rs.metadata.namespace, rs.metadata.name
                )
            except NotFound:
                pass

    # -- status ---------------------------------------------------------------

    def _sync_status(
        self, dep: v1.Deployment, new_rs: v1.ReplicaSet, old_rss: List[v1.ReplicaSet]
    ) -> None:
        all_rss = [new_rs] + old_rss
        replicas = sum(rs.status.replicas for rs in all_rss)
        ready = sum(rs.status.ready_replicas for rs in all_rss)
        upd = self._ready_by_rs(dep).get(new_rs.metadata.name, 0)

        def mutate(cur):
            st = cur.status
            if (
                st.replicas == replicas
                and st.ready_replicas == ready
                and st.updated_replicas == upd
                and st.observed_generation == cur.metadata.generation
            ):
                return None
            st.replicas = replicas
            st.ready_replicas = ready
            st.available_replicas = ready
            st.unavailable_replicas = max(0, cur.spec.replicas - ready)
            st.updated_replicas = upd
            st.observed_generation = cur.metadata.generation
            return cur

        try:
            self.server.guaranteed_update(
                "deployments", dep.metadata.namespace, dep.metadata.name, mutate
            )
        except NotFound:
            pass
