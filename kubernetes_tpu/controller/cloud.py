"""cloud-controller-manager: service load balancers + routes against a
cloud-provider interface.

Reference: cmd/cloud-controller-manager + pkg/controller/cloud +
staging/src/k8s.io/cloud-provider — the cloud loops talk to a provider
interface (LoadBalancer / Routes / Instances); kubernetes ships the
interface and providers implement it. Here ``FakeCloudProvider`` is the
in-tree test provider equivalent (cloud-provider/fake): an in-memory
cloud whose state the tests can inspect.

Loops:
  * ServiceLBController — Services of type LoadBalancer get a provisioned
    cloud LB (external IP written back to spec.external_ips); deleting the
    service or flipping its type tears the LB down.
  * RouteController — one cloud route per node pod CIDR
    (pkg/controller/route): created when nodeipam assigns the CIDR,
    removed with the node.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.cloud")


class FakeCloudProvider:
    """In-memory cloud (cloud-provider/fake equivalent)."""

    def __init__(self, lb_prefix: str = "203.0.113"):
        self._lock = threading.Lock()
        self.load_balancers: Dict[str, str] = {}  # service key -> external IP
        self.routes: Dict[str, str] = {}  # node name -> pod CIDR
        self._next_lb = 1
        self.lb_prefix = lb_prefix

    # LoadBalancer interface
    def ensure_load_balancer(self, service_key: str) -> str:
        with self._lock:
            ip = self.load_balancers.get(service_key)
            if ip is None:
                ip = f"{self.lb_prefix}.{self._next_lb}"
                self._next_lb += 1
                self.load_balancers[service_key] = ip
            return ip

    def delete_load_balancer(self, service_key: str) -> None:
        with self._lock:
            self.load_balancers.pop(service_key, None)

    # Routes interface
    def create_route(self, node: str, cidr: str) -> None:
        with self._lock:
            self.routes[node] = cidr

    def delete_route(self, node: str) -> None:
        with self._lock:
            self.routes.pop(node, None)

    def list_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self.routes)


class ServiceLBController(WorkqueueController):
    name = "service-lb"
    primary_kind = "services"
    secondary_kinds = ()

    def __init__(self, server, cloud: Optional[FakeCloudProvider] = None, workers: int = 1):
        super().__init__(server, workers=workers)
        self.cloud = cloud or FakeCloudProvider()

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            svc = self.server.get("services", ns, name)
        except NotFound:
            self.cloud.delete_load_balancer(key)
            return
        if svc.spec.type != "LoadBalancer":
            if key in self.cloud.load_balancers:
                self.cloud.delete_load_balancer(key)
                self._set_external_ips(ns, name, [])
            return
        ip = self.cloud.ensure_load_balancer(key)
        if ip not in svc.spec.external_ips:
            self._set_external_ips(ns, name, [ip])

    def _set_external_ips(self, ns: str, name: str, ips) -> None:
        def mutate(s):
            if s.spec.external_ips == ips:
                return None
            s.spec.external_ips = list(ips)
            return s

        try:
            self.server.guaranteed_update("services", ns, name, mutate)
        except NotFound:
            pass


class RouteController(WorkqueueController):
    name = "route"
    primary_kind = "nodes"
    secondary_kinds = ()

    def __init__(self, server, cloud: Optional[FakeCloudProvider] = None, workers: int = 1):
        super().__init__(server, workers=workers)
        self.cloud = cloud or FakeCloudProvider()

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            node = self.server.get("nodes", ns, name)
        except NotFound:
            self.cloud.delete_route(name)
            return
        if node.spec.pod_cidr:
            if self.cloud.list_routes().get(name) != node.spec.pod_cidr:
                self.cloud.create_route(name, node.spec.pod_cidr)
