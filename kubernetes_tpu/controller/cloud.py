"""cloud-controller-manager: service load balancers + routes against a
cloud-provider interface.

Reference: cmd/cloud-controller-manager + pkg/controller/cloud +
staging/src/k8s.io/cloud-provider — the cloud loops talk to a provider
interface (LoadBalancer / Routes / Instances); kubernetes ships the
interface and providers implement it. Here ``FakeCloudProvider`` is the
in-tree test provider equivalent (cloud-provider/fake): an in-memory
cloud whose state the tests can inspect.

Loops:
  * ServiceLBController — Services of type LoadBalancer get a provisioned
    cloud LB (ingress IP in status.loadBalancer + spec.external_ips, LB
    backend hosts kept in step with ready nodes); deleting the service or
    flipping its type tears the LB down.
  * RouteController — one cloud route per node pod CIDR
    (pkg/controller/route): created when nodeipam assigns the CIDR,
    removed with the node.
  * CloudNodeController — initializes new nodes from cloud instance
    metadata: clears the cloudprovider uninitialized taint, sets
    providerID, instance-type/zone labels and node addresses
    (pkg/controller/cloud/node_controller.go).
  * CloudNodeLifecycleController — periodically verifies each node's
    instance still exists in the cloud; gone -> the Node object is
    deleted, shutdown -> the shutdown taint
    (pkg/controller/cloud/node_lifecycle_controller.go).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.cloud")

# the cloud taints (cloud-provider api/well_known_taints.go): new nodes
# register with the uninitialized taint until the cloud controller
# initializes them; shutdown instances get the shutdown taint
TAINT_UNINITIALIZED = "node.cloudprovider.kubernetes.io/uninitialized"
TAINT_SHUTDOWN = "node.cloudprovider.kubernetes.io/shutdown"


class CloudInstance:
    """One cloud VM's metadata (cloud-provider Instances record)."""

    __slots__ = (
        "provider_id", "instance_type", "zone", "addresses", "exists",
        "shutdown",
    )

    def __init__(
        self,
        provider_id: str = "",
        instance_type: str = "tpu.standard-4",
        zone: str = "zone-a",
        addresses: Optional[Tuple[Tuple[str, str], ...]] = None,  # (type, addr)
        exists: bool = True,
        shutdown: bool = False,
    ):
        self.provider_id = provider_id
        self.instance_type = instance_type
        self.zone = zone
        self.addresses = addresses or ()
        self.exists = exists
        self.shutdown = shutdown


class FakeCloudProvider:
    """In-memory cloud (cloud-provider/fake equivalent): LoadBalancer,
    Routes and Instances interfaces."""

    def __init__(self, lb_prefix: str = "203.0.113"):
        self._lock = threading.Lock()
        self.load_balancers: Dict[str, str] = {}  # service key -> external IP
        self.lb_hosts: Dict[str, Tuple[str, ...]] = {}  # svc key -> node names
        self.routes: Dict[str, str] = {}  # node name -> pod CIDR
        self.instances: Dict[str, CloudInstance] = {}  # node name -> VM
        self._next_lb = 1
        self.lb_prefix = lb_prefix

    # LoadBalancer interface
    def ensure_load_balancer(self, service_key: str, hosts=()) -> str:
        with self._lock:
            ip = self.load_balancers.get(service_key)
            if ip is None:
                ip = f"{self.lb_prefix}.{self._next_lb}"
                self._next_lb += 1
                self.load_balancers[service_key] = ip
            self.lb_hosts[service_key] = tuple(hosts)
            return ip

    def update_load_balancer_hosts(self, service_key: str, hosts) -> None:
        with self._lock:
            if service_key in self.load_balancers:
                self.lb_hosts[service_key] = tuple(hosts)

    def delete_load_balancer(self, service_key: str) -> None:
        with self._lock:
            self.load_balancers.pop(service_key, None)
            self.lb_hosts.pop(service_key, None)

    # Routes interface
    def create_route(self, node: str, cidr: str) -> None:
        with self._lock:
            self.routes[node] = cidr

    def delete_route(self, node: str) -> None:
        with self._lock:
            self.routes.pop(node, None)

    def list_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self.routes)

    # Instances interface
    def add_instance(self, node: str, inst: Optional[CloudInstance] = None) -> CloudInstance:
        with self._lock:
            i = inst or CloudInstance(provider_id=f"fake://{node}")
            if not i.provider_id:
                i.provider_id = f"fake://{node}"
            self.instances[node] = i
            return i

    def instance(self, node: str) -> Optional[CloudInstance]:
        with self._lock:
            return self.instances.get(node)

    def instance_exists(self, node: str) -> bool:
        with self._lock:
            i = self.instances.get(node)
            return i is not None and i.exists

    def instance_shutdown(self, node: str) -> bool:
        with self._lock:
            i = self.instances.get(node)
            return i is not None and i.shutdown


class ServiceLBController(WorkqueueController):
    name = "service-lb"
    primary_kind = "services"
    # node events refresh every LB's backend host set (the reference's
    # service controller watches nodes for exactly this)
    secondary_kinds = ("nodes",)

    def __init__(self, server, cloud: Optional[FakeCloudProvider] = None, workers: int = 1):
        super().__init__(server, workers=workers)
        self.cloud = cloud or FakeCloudProvider()

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        if resource == "nodes":
            # host-set refresh is world-scoped, not per-service: do it
            # inline (cheap: one node list per burst of node events) and
            # requeue nothing
            try:
                self.sync_hosts()
            except Exception:
                logger.exception("LB host sync failed")
            return None
        return None

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            svc = self.server.get("services", ns, name)
        except NotFound:
            self.cloud.delete_load_balancer(key)
            return
        if svc.spec.type != "LoadBalancer":
            if key in self.cloud.load_balancers:
                self.cloud.delete_load_balancer(key)
                self._set_external_ips(ns, name, [])
            return
        ip = self.cloud.ensure_load_balancer(key, hosts=self._ready_nodes())
        if ip not in svc.spec.external_ips:
            self._set_external_ips(ns, name, [ip])

    def _ready_nodes(self):
        """LB backend hosts = schedulable Ready nodes (the reference's
        host-set the service controller keeps in step on node changes)."""
        try:
            nodes, _ = self.server.list("nodes")
        except Exception:
            return ()
        out = []
        for n in nodes:
            if n.spec.unschedulable:
                continue
            ready = any(
                c.type == v1.NODE_READY and c.status == "True"
                for c in n.status.conditions
            )
            if ready:
                out.append(n.metadata.name)
        return tuple(sorted(out))

    def sync_hosts(self) -> None:
        """Node-change hook: refresh every provisioned LB's host set
        (UpdateLoadBalancerHosts on node add/remove/readiness flip)."""
        hosts = self._ready_nodes()
        for key in list(self.cloud.load_balancers):
            self.cloud.update_load_balancer_hosts(key, hosts)

    def _set_external_ips(self, ns: str, name: str, ips) -> None:
        def mutate(s):
            if (
                s.spec.external_ips == list(ips)
                and s.status.load_balancer.ingress == list(ips)
            ):
                return None
            s.spec.external_ips = list(ips)
            s.status.load_balancer.ingress = list(ips)
            return s

        try:
            self.server.guaranteed_update("services", ns, name, mutate)
        except NotFound:
            pass


class RouteController(WorkqueueController):
    name = "route"
    primary_kind = "nodes"
    secondary_kinds = ()

    def __init__(self, server, cloud: Optional[FakeCloudProvider] = None, workers: int = 1):
        super().__init__(server, workers=workers)
        self.cloud = cloud or FakeCloudProvider()

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            node = self.server.get("nodes", ns, name)
        except NotFound:
            self.cloud.delete_route(name)
            return
        if node.spec.pod_cidr:
            if self.cloud.list_routes().get(name) != node.spec.pod_cidr:
                self.cloud.create_route(name, node.spec.pod_cidr)


class CloudNodeController(WorkqueueController):
    """Node initialization from cloud metadata
    (pkg/controller/cloud/node_controller.go): a kubelet registering with
    --cloud-provider=external adds the uninitialized taint; this loop
    looks the instance up, stamps providerID / instance-type and zone
    labels / addresses, and removes the taint so the node becomes
    schedulable."""

    name = "cloud-node"
    primary_kind = "nodes"
    secondary_kinds = ()

    LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
    LABEL_ZONE = "topology.kubernetes.io/zone"

    def __init__(self, server, cloud: Optional[FakeCloudProvider] = None, workers: int = 1):
        super().__init__(server, workers=workers)
        self.cloud = cloud or FakeCloudProvider()

    def sync(self, key: str) -> None:
        _ns, _, name = key.rpartition("/")
        try:
            node = self.server.get("nodes", "", name)
        except NotFound:
            return
        if not any(t.key == TAINT_UNINITIALIZED for t in node.spec.taints):
            return
        inst = self.cloud.instance(name)
        if inst is None or not inst.exists:
            return  # not in the cloud yet: retried on the next node event

        def mutate(n):
            n.spec.taints = [
                t for t in n.spec.taints if t.key != TAINT_UNINITIALIZED
            ]
            n.spec.provider_id = inst.provider_id
            n.metadata.labels.setdefault(
                self.LABEL_INSTANCE_TYPE, inst.instance_type
            )
            n.metadata.labels.setdefault(self.LABEL_ZONE, inst.zone)
            if inst.addresses:
                # NodeStatus.addresses rows are (type, address) pairs
                n.status.addresses = [tuple(a) for a in inst.addresses]
            return n

        try:
            self.server.guaranteed_update("nodes", "", name, mutate)
        except NotFound:
            pass


class CloudNodeLifecycleController:
    """Instance-existence sweep
    (pkg/controller/cloud/node_lifecycle_controller.go): nodes whose
    cloud instance is GONE are deleted from the API (their pods then ride
    the normal nodelifecycle eviction); SHUTDOWN instances get the
    shutdown NoSchedule taint until they come back. Runs as a periodic
    monitor, not a workqueue — existence is a cloud-side fact with no
    API event to react to."""

    def __init__(
        self,
        server,
        cloud: Optional[FakeCloudProvider] = None,
        period_s: float = 5.0,
    ):
        self.server = server
        self.cloud = cloud or FakeCloudProvider()
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cloud-node-lifecycle"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sweep()
            except Exception:
                logger.exception("cloud node lifecycle sweep failed")

    def sweep(self) -> None:  # graftlint: degraded-ok(the run loop catches everything and retries the sweep next period)
        try:
            nodes, _ = self.server.list("nodes")
        except Exception:
            return
        for node in nodes:
            name = node.metadata.name
            if self.cloud.instance(name) is None:
                continue  # never cloud-managed (e.g. not registered)
            if not self.cloud.instance_exists(name):
                logger.info("node %s gone from the cloud; deleting", name)
                try:
                    self.server.delete("nodes", "", name)
                except NotFound:
                    pass
                continue
            shutdown = self.cloud.instance_shutdown(name)
            has_taint = any(
                t.key == TAINT_SHUTDOWN for t in node.spec.taints
            )
            if shutdown == has_taint:
                continue

            def mutate(n, want=shutdown):
                if want:
                    n.spec.taints = list(n.spec.taints) + [
                        v1.Taint(key=TAINT_SHUTDOWN, effect=v1.TAINT_NO_SCHEDULE)
                    ]
                else:
                    n.spec.taints = [
                        t for t in n.spec.taints if t.key != TAINT_SHUTDOWN
                    ]
                return n

            try:
                self.server.guaranteed_update("nodes", "", name, mutate)
            except NotFound:
                pass
