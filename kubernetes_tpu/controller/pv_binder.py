"""PersistentVolume binder controller: match pending claims to volumes.

Reference: pkg/controller/volume/persistentvolume (pv_controller.go
syncUnboundClaim / syncVolume) — for every Pending PVC with immediate
binding: find the smallest Available PV satisfying class, access modes and
capacity; bind both sides (pv.spec.claimRef <-> pvc.spec.volumeName) and
set both phases Bound. Claims in WaitForFirstConsumer classes are left for
the scheduler's VolumeBinding plugin (controller/volume_scheduling.py).
Deleted claims release their volume (Released; no reclaim policies here).
Classes with a provisioner dynamically create a matching PV first — the
in-process analogue of scheduler_perf's StartFakePVController
(test/integration/util/util.go:110).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api import objects as v1
from ..api.resources import parse_quantity
from ..client.apiserver import AlreadyExists, NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.pv_binder")


class PVBinderController(WorkqueueController):
    name = "persistentvolume-binder"
    primary_kind = "persistentvolumeclaims"
    secondary_kinds = ("persistentvolumes",)

    def enqueue_for_related(self, resource: str, obj) -> Optional[str]:
        # a PV event re-queues every pending claim (cheap: claims are few)
        claims, _ = self.server.list("persistentvolumeclaims")
        for c in claims:
            if c.status.phase == v1.CLAIM_PENDING:
                self.queue.add(c.metadata.key)
        return None

    # -- reconcile ------------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, _, name = key.partition("/")
        try:
            pvc = self.server.get("persistentvolumeclaims", ns, name)
        except NotFound:
            self._release_volume_of(key)
            return
        if pvc.spec.volume_name:
            self._ensure_bound_phases(pvc)
            return
        sc = self._class_of(pvc)
        if sc is not None and sc.volume_binding_mode == "WaitForFirstConsumer":
            return  # the scheduler binds these at placement time
        pv = self._find_available_pv(pvc)
        if pv is None and sc is not None and sc.provisioner:
            pv = self._provision(pvc, sc)
        if pv is None:
            return  # stay Pending; retried on PV events
        self._bind(pvc, pv)

    # -- helpers --------------------------------------------------------------

    def _class_of(self, pvc) -> Optional[v1.StorageClass]:
        if not pvc.spec.storage_class_name:
            return None
        try:
            return self.server.get(
                "storageclasses", "", pvc.spec.storage_class_name
            )
        except NotFound:
            try:
                return self.server.get(
                    "storageclasses", "default", pvc.spec.storage_class_name
                )
            except NotFound:
                return None

    def _find_available_pv(self, pvc) -> Optional[v1.PersistentVolume]:
        pvs, _ = self.server.list("persistentvolumes")
        want = parse_quantity(pvc.spec.resources.get("storage", 0))
        cands = []
        for pv in pvs:
            if pv.spec.claim_ref or pv.status.phase != "Available":
                continue
            if (pv.spec.storage_class_name or "") != (
                pvc.spec.storage_class_name or ""
            ):
                continue
            if pvc.spec.access_modes and not set(pvc.spec.access_modes) <= set(
                pv.spec.access_modes
            ):
                continue
            cap = parse_quantity(pv.spec.capacity.get("storage", 0))
            if cap < want:
                continue
            cands.append((cap, pv))
        # smallest satisfying volume (pv_controller's findBestMatch)
        return min(cands, key=lambda t: t[0])[1] if cands else None

    def _provision(self, pvc, sc) -> Optional[v1.PersistentVolume]:
        pv = v1.PersistentVolume(
            metadata=v1.ObjectMeta(name=f"pvc-{pvc.metadata.uid}", namespace=""),
            spec=v1.PersistentVolumeSpec(
                capacity={"storage": pvc.spec.resources.get("storage", "1Gi")},
                access_modes=list(pvc.spec.access_modes) or ["ReadWriteOnce"],
                storage_class_name=pvc.spec.storage_class_name or "",
                csi=v1.CSIVolumeSource(
                    driver=sc.provisioner, volume_handle=f"pvc-{pvc.metadata.uid}"
                ),
            ),
        )
        try:
            return self.server.create("persistentvolumes", pv)
        except AlreadyExists:
            try:
                return self.server.get(
                    "persistentvolumes", "", pv.metadata.name
                )
            except NotFound:
                return None

    def _bind(self, pvc, pv) -> None:
        claim_key = pvc.metadata.key

        def bind_pv(p):
            if p.spec.claim_ref and p.spec.claim_ref != claim_key:
                return None  # raced: another claim took it
            p.spec.claim_ref = claim_key
            p.status.phase = "Bound"
            return p

        try:
            updated = self.server.guaranteed_update(
                "persistentvolumes", pv.metadata.namespace, pv.metadata.name, bind_pv
            )
        except NotFound:
            return
        if updated.spec.claim_ref != claim_key:
            return  # lost the race; the claim retries on the next PV event

        def bind_pvc(c):
            c.spec.volume_name = pv.metadata.name
            c.status.phase = v1.CLAIM_BOUND
            # provisioned size baseline the expand controller compares
            # spec.resources against (pv_controller's bindClaimToVolume
            # copies volume capacity into claim status)
            if "storage" in pv.spec.capacity:
                c.status.capacity["storage"] = pv.spec.capacity["storage"]
            return c

        try:
            self.server.guaranteed_update(
                "persistentvolumeclaims",
                pvc.metadata.namespace,
                pvc.metadata.name,
                bind_pvc,
            )
        except NotFound:
            # claim vanished mid-bind: release the volume again
            self._release(pv.metadata)

    def _ensure_bound_phases(self, pvc) -> None:
        if pvc.status.phase != v1.CLAIM_BOUND:
            def mark(c):
                if c.status.phase == v1.CLAIM_BOUND:
                    return None
                c.status.phase = v1.CLAIM_BOUND
                return c

            try:
                self.server.guaranteed_update(
                    "persistentvolumeclaims",
                    pvc.metadata.namespace,
                    pvc.metadata.name,
                    mark,
                )
            except NotFound:
                pass

    def _release_volume_of(self, claim_key: str) -> None:
        pvs, _ = self.server.list("persistentvolumes")
        for pv in pvs:
            if pv.spec.claim_ref == claim_key:
                def release(p):
                    p.spec.claim_ref = None
                    p.status.phase = "Released"
                    return p

                try:
                    self.server.guaranteed_update(
                        "persistentvolumes",
                        pv.metadata.namespace,
                        pv.metadata.name,
                        release,
                    )
                except NotFound:
                    pass

    def _release(self, pv_meta) -> None:
        def release(p):
            p.spec.claim_ref = None
            p.status.phase = "Available"
            return p

        try:
            self.server.guaranteed_update(
                "persistentvolumes", pv_meta.namespace, pv_meta.name, release
            )
        except NotFound:
            pass
