"""Certificates controller: CSR auto-approval + signing.

Reference: pkg/controller/certificates/{approver,signer} — the approver
auto-approves kubelet client CSRs from recognized bootstrap identities
(sarapprove), the signer issues the certificate for approved CSRs. This
build has no x509 machinery; the issued credential is an HMAC over the
request bound to the cluster trust root, which the TokenAuthenticator
accepts the same way it accepts ServiceAccount tokens — same
trust-establishment flow, different crypto.
"""

from __future__ import annotations

import hashlib
import hmac
import logging

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.certificates")

APPROVED = "Approved"
DENIED = "Denied"
KUBELET_SIGNER = "kubernetes.io/kube-apiserver-client-kubelet"
AUTO_APPROVE_GROUPS = {"system:bootstrappers", "system:nodes"}


def _condition(csr: v1.CertificateSigningRequest, cond_type: str) -> bool:
    return any(
        c.type == cond_type and c.status == "True" for c in csr.status.conditions
    )


class CSRSigningController(WorkqueueController):
    """Approve + sign in one loop (the reference runs approver and signer
    as two controllers over the same resource; one loop keeps the state
    machine in a single place here)."""

    name = "csrsigning"
    primary_kind = "certificatesigningrequests"
    secondary_kinds = ()

    def __init__(self, server, workers: int = 1, signing_key: bytes = b"tpu-cluster-trust-root"):
        super().__init__(server, workers=workers)
        self.signing_key = signing_key

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            csr = self.server.get("certificatesigningrequests", ns, name)
        except NotFound:
            return
        if _condition(csr, DENIED) or csr.status.certificate:
            return

        if not _condition(csr, APPROVED):
            # sarapprove: kubelet-client CSRs from bootstrap identities
            if csr.spec.signer_name == KUBELET_SIGNER and (
                AUTO_APPROVE_GROUPS & set(csr.spec.groups)
            ):
                self._set_condition(ns, name, APPROVED, "AutoApproved")
            return  # signing happens on the next sync after approval

        issued = hmac.new(
            self.signing_key,
            f"{csr.spec.username}:{csr.spec.request}".encode(),
            hashlib.sha256,
        ).hexdigest()

        def sign(cur):
            if cur.status.certificate:
                return None
            cur.status.certificate = issued
            return cur

        try:
            self.server.guaranteed_update(
                "certificatesigningrequests", ns, name, sign
            )
        except NotFound:
            pass

    def _set_condition(self, ns: str, name: str, cond_type: str, reason: str) -> None:
        def mutate(cur):
            if _condition(cur, cond_type):
                return None
            cur.status.conditions.append(
                v1.PodCondition(type=cond_type, status="True", reason=reason)
            )
            return cur

        try:
            self.server.guaranteed_update(
                "certificatesigningrequests", ns, name, mutate
            )
        except NotFound:
            pass
