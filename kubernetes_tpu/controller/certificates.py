"""Certificates controller: CSR auto-approval + signing.

Reference: pkg/controller/certificates/{approver,signer} — the approver
auto-approves kubelet client CSRs from recognized bootstrap identities
(sarapprove), the signer issues the certificate for approved CSRs. This
build has no x509 machinery; the issued credential is an HMAC over the
request bound to the cluster trust root, which the TokenAuthenticator
accepts the same way it accepts ServiceAccount tokens — same
trust-establishment flow, different crypto.
"""

from __future__ import annotations

import hashlib
import hmac
import logging

from ..api import objects as v1
from ..client.apiserver import NotFound
from .base import WorkqueueController

logger = logging.getLogger("kubernetes_tpu.controller.certificates")

APPROVED = "Approved"
DENIED = "Denied"
KUBELET_SIGNER = "kubernetes.io/kube-apiserver-client-kubelet"
AUTO_APPROVE_GROUPS = {"system:bootstrappers", "system:nodes"}


def _condition(csr: v1.CertificateSigningRequest, cond_type: str) -> bool:
    return any(
        c.type == cond_type and c.status == "True" for c in csr.status.conditions
    )


class CSRApprovingController(WorkqueueController):
    """Auto-approval loop (pkg/controller/certificates/approver/
    sarapprove.go): kubelet client CSRs from recognized bootstrap
    identities get the Approved condition; everything else waits for a
    human (kubectl certificate approve)."""

    name = "csrapproving"
    primary_kind = "certificatesigningrequests"
    secondary_kinds = ()

    def __init__(self, server, workers: int = 1):
        super().__init__(server, workers=workers)

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            csr = self.server.get("certificatesigningrequests", ns, name)
        except NotFound:
            return
        if _condition(csr, APPROVED) or _condition(csr, DENIED):
            return
        if csr.spec.signer_name == KUBELET_SIGNER and (
            AUTO_APPROVE_GROUPS & set(csr.spec.groups)
        ):
            _set_condition(self.server, ns, name, APPROVED, "AutoApproved")


class CSRSigningController(WorkqueueController):
    """Signing loop (pkg/controller/certificates/signer): issues the
    credential for Approved CSRs. Approval itself is the approver's job."""

    name = "csrsigning"
    primary_kind = "certificatesigningrequests"
    secondary_kinds = ()

    def __init__(self, server, workers: int = 1, signing_key: bytes = b"tpu-cluster-trust-root"):
        super().__init__(server, workers=workers)
        self.signing_key = signing_key

    def sync(self, key: str) -> None:
        ns, _, name = key.rpartition("/")
        try:
            csr = self.server.get("certificatesigningrequests", ns, name)
        except NotFound:
            return
        if _condition(csr, DENIED) or csr.status.certificate:
            return
        if not _condition(csr, APPROVED):
            return  # signing happens on the sync after approval

        issued = hmac.new(
            self.signing_key,
            f"{csr.spec.username}:{csr.spec.request}".encode(),
            hashlib.sha256,
        ).hexdigest()

        def sign(cur):
            if cur.status.certificate:
                return None
            cur.status.certificate = issued
            return cur

        try:
            self.server.guaranteed_update(
                "certificatesigningrequests", ns, name, sign
            )
        except NotFound:
            pass

def _set_condition(server, ns: str, name: str, cond_type: str, reason: str) -> None:  # graftlint: degraded-ok(only called from WorkqueueController sync paths: the worker loop catches and requeues rate-limited)
    def mutate(cur):
        if _condition(cur, cond_type):
            return None
        cur.status.conditions.append(
            v1.PodCondition(type=cond_type, status="True", reason=reason)
        )
        return cur

    try:
        server.guaranteed_update("certificatesigningrequests", ns, name, mutate)
    except NotFound:
        pass


class CSRCleanerController(WorkqueueController):
    """Garbage-collect stale CSRs (pkg/controller/certificates/cleaner/
    cleaner.go): signed or denied requests past their retention window and
    pending requests nobody acted on are deleted on a poll tick."""

    name = "csrcleaner"
    primary_kind = "certificatesigningrequests"
    secondary_kinds = ()

    def __init__(
        self,
        server,
        workers: int = 1,
        tick: float = 60.0,
        signed_ttl: float = 3600.0,     # approved + issued (1h)
        denied_ttl: float = 3600.0,     # denied (1h)
        pending_ttl: float = 24 * 3600.0,  # never acted on (24h)
    ):
        super().__init__(server, workers=workers)
        self.tick = tick
        self.signed_ttl = signed_ttl
        self.denied_ttl = denied_ttl
        self.pending_ttl = pending_ttl

    def start(self) -> None:
        super().start()
        # expiry is time-driven, not event-driven
        self.start_ticker("csrcleaner-tick", self.tick, self._enqueue_all)

    def _enqueue_all(self) -> None:
        for csr in self.server.list("certificatesigningrequests")[0]:
            self.queue.add(csr.metadata.key)

    def sync(self, key: str) -> None:
        import time as _time

        ns, _, name = key.rpartition("/")
        try:
            csr = self.server.get("certificatesigningrequests", ns, name)
        except NotFound:
            return
        age = _time.time() - csr.metadata.creation_timestamp
        if _condition(csr, DENIED):
            expired = age > self.denied_ttl
        elif _condition(csr, APPROVED) and csr.status.certificate:
            expired = age > self.signed_ttl
        elif not csr.status.conditions:
            expired = age > self.pending_ttl
        else:
            return  # approved-but-unsigned: the signer still owes it work
        if expired:
            try:
                self.server.delete("certificatesigningrequests", ns, name)
                logger.info("csrcleaner: deleted stale CSR %s", key)
            except NotFound:
                pass
