"""Kernel-driven cluster autoscaler.

Scale-up and scale-down decisions are batched what-if evaluations of the
PRODUCTION lattice kernel against a copy-on-append overlay of the HBM
snapshot (virtual candidate rows / masked drain rows) — no re-implemented
plugin logic, no second constraint model to drift. See planner.py for the
simulation machinery and controller.py for the loop.
"""

from .controller import ClusterAutoscaler, autoscaler_health_lines
from .nodegroups import NodeGroup, NodeGroupCatalog, machine_shape
from .planner import (
    ScaleUpPlan,
    WhatIfSimulator,
    pack_weights,
    plan_scale_up,
    simulate_drain,
)

__all__ = [
    "ClusterAutoscaler",
    "NodeGroup",
    "NodeGroupCatalog",
    "ScaleUpPlan",
    "WhatIfSimulator",
    "autoscaler_health_lines",
    "machine_shape",
    "pack_weights",
    "plan_scale_up",
    "simulate_drain",
]
