"""NodeGroup catalog: the candidate machine shapes the autoscaler may add.

Reference: the cluster-autoscaler's cloudprovider.NodeGroup contract
(TemplateNodeInfo / IncreaseSize / DeleteNodes) — a group is a homogeneous
pool of a single machine shape with [min_size, max_size] bounds. Here a
group's shape is simply a `v1.Node` template function; what-if simulation
encodes the template into virtual snapshot rows (ops/encoding.whatif_
overlay), so the SAME columnar encoding that drives live scheduling
describes candidate capacity — no parallel machine-type model to drift.

Provisioning is pluggable: by default a scale-up just creates the Node
object through the apiserver (the perf harness's store-acked world); tests
and the kubemark rig pass hooks that also start a hollow kubelet for the
new node (`kubemark.HollowCluster.provisioner_for`), so the node
heartbeats and accepts binds like any fleet member.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..api import objects as v1
from ..api.objects import LABEL_NODEGROUP


def machine_shape(
    cpu: str = "4",
    memory: str = "32Gi",
    pods: int = 110,
    labels: Optional[dict] = None,
    taints: Optional[list] = None,
    cost_per_hour: Optional[float] = None,
    accelerator_class: Optional[str] = None,
    energy_watts: Optional[float] = None,
) -> Callable[[str], v1.Node]:
    """Node template for a homogeneous machine shape (the moral equivalent
    of cloudprovider TemplateNodeInfo). cost_per_hour / accelerator_class /
    energy_watts stamp the encoder's heterogeneity-column labels
    (ops/encoding.LABEL_*), so the SAME columns drive live scoring
    policies and the autoscaler's cheapest-feasible-shape packing."""
    from ..ops.encoding import (
        LABEL_ACCELERATOR_CLASS,
        LABEL_COST_PER_HOUR,
        LABEL_ENERGY_WATTS,
    )

    shape_labels = dict(labels or {})
    if cost_per_hour is not None:
        shape_labels[LABEL_COST_PER_HOUR] = str(cost_per_hour)
    if accelerator_class is not None:
        shape_labels[LABEL_ACCELERATOR_CLASS] = accelerator_class
    if energy_watts is not None:
        shape_labels[LABEL_ENERGY_WATTS] = str(energy_watts)

    def template(name: str) -> v1.Node:
        return v1.Node(
            metadata=v1.ObjectMeta(
                name=name, namespace="", labels=dict(shape_labels)
            ),
            spec=v1.NodeSpec(taints=list(taints or [])),
            status=v1.NodeStatus(
                capacity={"cpu": cpu, "memory": memory, "pods": pods},
                allocatable={"cpu": cpu, "memory": memory, "pods": pods},
                conditions=[
                    v1.NodeCondition(type=v1.NODE_READY, status="True")
                ],
            ),
        )

    return template


@dataclass
class NodeGroup:
    """One scalable pool of a single machine shape.

    provision(name) must make the node REAL: create the Node object (and,
    on rigs with kubelets, start one for it). deprovision(name) tears the
    node's agent down after scale-down deleted the object. Both default to
    apiserver-only behavior supplied by the controller."""

    name: str
    template: Callable[[str], v1.Node]
    min_size: int = 0
    max_size: int = 1000
    provision: Optional[Callable[[str], object]] = None
    deprovision: Optional[Callable[[str], object]] = None
    _counter: itertools.count = field(
        default_factory=itertools.count, repr=False
    )

    def make_node(self, name: str) -> v1.Node:
        """Instantiate the template and stamp the group label (how
        scale-down attributes a live node back to this group)."""
        node = self.template(name)
        node.metadata.labels[LABEL_NODEGROUP] = self.name
        return node

    def cost_per_hour(self) -> float:
        """The shape's cost-per-hour from its template's heterogeneity
        label (0.0 when unlabeled) — the autoscaler_shape_cost_* metric
        source and the hetero bench's fleet-cost accounting."""
        from ..ops.encoding import LABEL_COST_PER_HOUR

        raw = self.template("__shape__").metadata.labels.get(
            LABEL_COST_PER_HOUR
        )
        try:
            return float(raw) if raw else 0.0
        except (TypeError, ValueError):
            return 0.0

    def next_name(self, taken) -> str:
        """Next collision-free node name for this group."""
        while True:
            name = f"{self.name}-{next(self._counter)}"
            if name not in taken:
                return name


class NodeGroupCatalog:
    """The ordered shape catalog a planner evaluates in one overlay pass."""

    def __init__(self, groups: List[NodeGroup]):
        if not groups:
            raise ValueError("catalog needs at least one NodeGroup")
        names = [g.name for g in groups]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate NodeGroup names: {names}")
        self.groups = list(groups)

    def group(self, name: str) -> Optional[NodeGroup]:
        return next((g for g in self.groups if g.name == name), None)

    def group_of_node(self, node: v1.Node) -> Optional[NodeGroup]:
        return self.group(node.metadata.labels.get(LABEL_NODEGROUP, ""))

    def sizes(self, nodes: List[v1.Node]) -> dict:
        """Live size per group, from the nodegroup label."""
        out = {g.name: 0 for g in self.groups}
        for n in nodes:
            gname = n.metadata.labels.get(LABEL_NODEGROUP)
            if gname in out:
                out[gname] += 1
        return out
