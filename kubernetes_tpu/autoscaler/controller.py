"""Cluster autoscaler controller: the loop around the what-if planner.

Reference shape: the cluster-autoscaler's RunOnce loop (core/static_
autoscaler.go) — scale-up from pending pods, scale-down from sustained
underutilization — with the decision engine swapped for batched kernel
what-if passes (planner.py) so capacity decisions use the SAME constraint
machinery as placement.

Per pass:
  1. **Scale-up**: snapshot the scheduler's unschedulableQ; if pods are
     pending (and no prior provisioning is still registering), run one
     overlay kernel pass over real + virtual rows and create exactly the
     Node objects the kernel used, through the apiserver. Hollow-node
     kubelets (kubemark) pick them up via the NodeGroup provision hook;
     the node-add informer event flushes unschedulableQ (failure-relative
     backoff — queue satellite), so pending pods bind within one period.
  2. **Scale-down**: nodes of a group, under the utilization threshold for
     `scale_down_unneeded_passes` consecutive passes, are drain-simulated
     (that node's row masked out). Only a PASSING simulation cordons; the
     drain then flows through the eviction token bucket (the PR-3
     limiter), re-verifying the simulation each pass, and the empty node
     is deleted + deprovisioned. A failing simulation never evicts
     anything (zero-eviction guarantee).

Degraded-store tolerance (PR-1/PR-3 discipline): every write that 503s
retryably is counted and skipped; the pass never dies on a read-only
store, and cordoned-but-undrained nodes resume next pass.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Set

from ..api import objects as v1
from ..api.objects import ANN_SAFE_TO_EVICT, LABEL_NODEGROUP
from ..api.resources import CPU, MEMORY, PODS
from ..client.apiserver import NotFound, NotPrimary
from ..controller.nodelifecycle import EvictionLimiter
from ..runtime.consensus import DegradedWrites
from ..utils.metrics import metrics
from .nodegroups import NodeGroup, NodeGroupCatalog
from .planner import (
    HIST_SIMULATION,
    WhatIfSimulator,
    plan_scale_up,
    simulate_drain,
)

logger = logging.getLogger("kubernetes_tpu.autoscaler")

GAUGE_PENDING = "autoscaler_pending_pods"
GAUGE_PROVISIONING = "autoscaler_provisioning_nodes"
GAUGE_DRAINING = "autoscaler_draining_nodes"
COUNTER_PROVISIONED = "autoscaler_nodes_provisioned_total"
COUNTER_REMOVED = "autoscaler_nodes_removed_total"
COUNTER_EVICTIONS = "autoscaler_evictions_total"
COUNTER_BLOCKED = "autoscaler_scale_down_blocked_total"
COUNTER_STORE_SKIPS = "autoscaler_degraded_write_skips_total"
COUNTER_UNPLACED = "autoscaler_unplaced_pods_total"
COUNTER_TRUNCATED = "autoscaler_truncated_pods_total"
# heterogeneity/cost observability: per-shape catalog price and the live
# fleet's aggregate cost-per-hour (the hetero bench's acceptance metric —
# cheapest-feasible-shape packing must show up as a strictly cheaper fleet)
GAUGE_SHAPE_COST = "autoscaler_shape_cost_per_hour"
GAUGE_SHAPE_COST_FLEET = "autoscaler_shape_cost_fleet_per_hour"

# stamped alongside the cordon so a restarted autoscaler can tell ITS
# drains from operator cordons: the in-memory _draining set dies with the
# process, and an unschedulable node it no longer recognizes would
# otherwise leak (never drained, never deleted, never uncordoned)
ANN_SCALE_DOWN = "autoscaler.kubernetes-tpu.io/scale-down"


class ClusterAutoscaler:
    def __init__(
        self,
        server,
        scheduler,
        catalog: NodeGroupCatalog,
        period_s: float = 1.0,
        max_provision_per_cycle: int = 16,
        scale_down_enabled: bool = True,
        scale_down_util_threshold: float = 0.3,
        scale_down_unneeded_passes: int = 3,
        eviction_qps: float = 10.0,
        eviction_burst: int = 5,
        provision_register_timeout_s: float = 30.0,
        cost_aware: bool = True,
        eviction_budget=None,
    ):
        self.server = server
        self.scheduler = scheduler
        self.catalog = catalog
        self.period = period_s
        self.max_per_cycle = max_provision_per_cycle
        self.scale_down_enabled = scale_down_enabled
        self.util_threshold = scale_down_util_threshold
        self.unneeded_passes = scale_down_unneeded_passes
        self.register_timeout = provision_register_timeout_s
        # eviction_budget: the process-wide shared bucket (controller/
        # evictionbudget.py) when this process also runs nodelifecycle /
        # preemption / the descheduler; private bucket otherwise
        self.limiter = eviction_budget or EvictionLimiter(
            eviction_qps, eviction_burst
        )
        self.sim = WhatIfSimulator(
            scheduler.cache,
            hard_pod_affinity_weight=scheduler.cfg.hard_pod_affinity_weight,
            cost_aware=cost_aware,
        )
        # shape economics: each group's cost-per-hour published once (the
        # fleet gauge tracks the live bill each pass, run_once)
        self._group_cost = {g.name: g.cost_per_hour() for g in catalog.groups}
        for g in catalog.groups:
            metrics.set_gauge(
                GAUGE_SHAPE_COST, self._group_cost[g.name], {"group": g.name}
            )
        # provisioned-but-not-yet-registered node names (+ deadline): while
        # non-empty, scale-up pauses — re-simulating against a snapshot
        # that can't see the nodes we JUST added would double-provision
        # for the same pods
        self._provisioning: Dict[str, float] = {}
        self._low_util_streak: Dict[str, int] = {}
        self._draining: Set[str] = set()
        # futility memo: a pass that provisioned NOTHING for this exact
        # pending set against this exact cluster state would re-run the
        # same multi-second simulation every period — skip until either
        # side changes (the encoder generation moves on any cluster
        # mutation, incl. our own provisions registering)
        self._futile: Optional[tuple] = None  # (pod-key frozenset, gen)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()  # restartable (stop() → start() cycles)
        self._thread = threading.Thread(
            target=self._run, name="cluster-autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:
                logger.exception("autoscaler pass failed")
            self._stop.wait(self.period)

    # -- one pass ------------------------------------------------------------

    def run_once(self) -> None:
        pending = [
            pi.pod
            for pi in self.scheduler.queue.unschedulable_pod_infos()
            if pi.pod.metadata.deletion_timestamp is None
        ]
        metrics.set_gauge(GAUGE_PENDING, float(len(pending)))
        self._reap_registered()
        if pending and not self._provisioning:
            self._scale_up(pending)
        if self.scale_down_enabled:
            self._scale_down_pass()
        metrics.set_gauge(GAUGE_PROVISIONING, float(len(self._provisioning)))
        metrics.set_gauge(GAUGE_DRAINING, float(len(self._draining)))
        # live fleet cost-per-hour from the cache's node set and the
        # catalog's shape prices (unlabeled / out-of-catalog nodes cost 0)
        fleet = 0.0
        try:
            for ni in self.scheduler.cache.node_infos().values():
                if ni.node is None:
                    continue
                gname = ni.node.metadata.labels.get(LABEL_NODEGROUP, "")
                fleet += self._group_cost.get(gname, 0.0)
        except Exception:
            logger.exception("fleet cost gauge pass failed")
        metrics.set_gauge(GAUGE_SHAPE_COST_FLEET, round(fleet, 6))

    def _reap_registered(self) -> None:
        """Drop provisioned nodes once the scheduler cache sees them (the
        snapshot can simulate against them from then on); time out the
        ones that never register so one lost provision can't wedge
        scale-up forever."""
        now = time.monotonic()
        for name, deadline in list(self._provisioning.items()):
            if self.scheduler.cache.get_node_info(name) is not None:
                del self._provisioning[name]
            elif now > deadline:
                logger.warning(
                    "provisioned node %s never registered; giving up", name
                )
                del self._provisioning[name]

    # -- scale-up ------------------------------------------------------------

    def _host_filter(self, pod: v1.Pod, ni) -> bool:
        """Production filter plugins for fallback (encoding-overflow) pods:
        the scheduler's pre-batch-sound subset against a virtual NodeInfo
        — the same plugin objects the live filter chain runs."""

        class _PI:
            __slots__ = ("pod",)

        pi = _PI()
        pi.pod = pod
        try:
            return self.scheduler._check_placement(pi, ni) is None
        except Exception:
            logger.exception("host filter failed for %s", pod.metadata.key)
            return False

    def _scale_up(self, pending: List[v1.Pod]) -> None:
        state = (
            frozenset(p.metadata.key for p in pending),
            self.scheduler.cache.encoder.generation,
        )
        if state == self._futile:
            return
        try:
            nodes, _ = self.server.list("nodes")
        except Exception:
            logger.exception("node list failed; skipping scale-up pass")
            return
        sizes = self.catalog.sizes(nodes)
        live_names = {n.metadata.name for n in nodes}
        plan = plan_scale_up(
            self.sim,
            self.catalog,
            pending,
            sizes,
            live_names,
            max_provision_per_cycle=self.max_per_cycle,
            host_filter=self._host_filter,
        )
        if plan.unplaced:
            metrics.inc(COUNTER_UNPLACED, by=float(plan.unplaced))
        if plan.truncated:
            # pods past the per-pass simulation width: not dropped — they
            # stay queued and the next pass (new cluster state after these
            # provisions register) picks them up — but say so
            metrics.inc(COUNTER_TRUNCATED, by=float(plan.truncated))
            logger.info(
                "scale-up pass simulated %d of %d pending pods "
                "(max_pods_per_pass); the rest plan next pass",
                len(pending) - plan.truncated, len(pending),
            )
        if not plan.total_nodes:
            if plan.skipped:
                logger.debug("scale-up skipped: %s", plan.skipped)
            self._futile = state
            return
        self._futile = None
        deadline = time.monotonic() + self.register_timeout
        for gname, names in plan.nodes.items():
            group = self.catalog.group(gname)
            for name in names:
                try:
                    self._provision_one(group, name)
                except (DegradedWrites, NotPrimary):
                    # read-only store: provisioning resumes when writes
                    # reopen (the pods stay pending, the next pass replans)
                    metrics.inc(COUNTER_STORE_SKIPS, {"write": "provision"})
                    return
                except Exception:
                    logger.exception("provisioning %s/%s failed", gname, name)
                    continue
                self._provisioning[name] = deadline
                metrics.inc(COUNTER_PROVISIONED, {"group": gname})
        logger.info(
            "scale-up: provisioned %d node(s) %s for %d pending pods "
            "(%d placed in simulation, %d unplaced by any shape, "
            "%d nodes over the per-cycle cap deferred)",
            plan.total_nodes, dict(plan.nodes), len(pending), plan.placed,
            plan.unplaced, plan.capped,
        )

    def _provision_one(self, group: NodeGroup, name: str) -> None:  # graftlint: degraded-ok(every call site sits in the scale-up loop's try: DegradedWrites is counted as a store-skip and the slot retries next cycle)
        if group.provision is not None:
            group.provision(name)
        else:
            self.server.create("nodes", group.make_node(name))

    # -- scale-down ----------------------------------------------------------

    def _utilization(self, ni) -> float:
        """max over cpu/mem/pod-count of requested/allocatable — the CA's
        node utilization measure, from the SAME aggregates the kernel's
        resource columns are built from."""
        out = 0.0
        for res in (CPU, MEMORY):
            alloc = ni.allocatable.get(res, 0)
            if alloc > 0:
                out = max(out, ni.requested.get(res, 0) / alloc)
        pod_cap = ni.allocatable.get(PODS, 0)
        if pod_cap > 0:
            out = max(out, len(ni.pods) / pod_cap)
        return out

    def _movable(self, pod: v1.Pod) -> bool:
        """A pod blocks scale-down unless a controller will recreate it
        (owner references — DaemonSet owners included: those pods are
        excluded from drain simulation AND eviction separately, in
        simulate_drain/_drain_one) or it is annotated safe-to-evict."""
        if pod.metadata.owner_references:
            return True
        return (
            pod.metadata.annotations.get(ANN_SAFE_TO_EVICT, "").lower()
            == "true"
        )

    def _scale_down_pass(self) -> None:
        cache = self.scheduler.cache
        try:
            nodes, _ = self.server.list("nodes")
        except Exception:
            logger.exception("node list failed; skipping scale-down pass")
            return
        sizes = self.catalog.sizes(nodes)
        infos = cache.node_infos()  # ONE lock acquisition per pass
        # adopt drains orphaned by a restart/leadership change: OUR cordon
        # annotation on an unschedulable group node we don't remember
        # means a previous incarnation was mid-drain
        for node in nodes:
            if (
                node.spec.unschedulable
                and node.metadata.name not in self._draining
                and node.metadata.annotations.get(ANN_SCALE_DOWN) == "true"
                and self.catalog.group_of_node(node) is not None
            ):
                logger.warning(
                    "adopting orphaned drain of %s (cordoned by a previous "
                    "autoscaler incarnation)", node.metadata.name,
                )
                self._draining.add(node.metadata.name)
        draining_by_group: Dict[str, int] = {}
        by_name = {n.metadata.name: n for n in nodes}
        for d in self._draining:
            dn = by_name.get(d)
            if dn is not None:
                g = dn.metadata.labels.get(LABEL_NODEGROUP, "")
                draining_by_group[g] = draining_by_group.get(g, 0) + 1
        live = set()
        for node in nodes:
            name = node.metadata.name
            live.add(name)
            if name in self._draining:
                continue
            group = self.catalog.group_of_node(node)
            ni = infos.get(name)
            if (
                group is None
                or ni is None
                or name in self._provisioning
                or node.spec.unschedulable
                or sizes.get(group.name, 0)
                - draining_by_group.get(group.name, 0)
                <= group.min_size
            ):
                self._low_util_streak.pop(name, None)
                continue
            if self._utilization(ni) > self.util_threshold:
                self._low_util_streak.pop(name, None)
                continue
            streak = self._low_util_streak.get(name, 0) + 1
            self._low_util_streak[name] = streak
            if streak < self.unneeded_passes:
                continue
            if self._try_cordon(node, ni):
                # count the new drain against the group's min_size floor
                # IMMEDIATELY: two same-pass candidates must not both
                # cordon past the floor (observed overshoot to 1 node
                # with min_size=2 before this)
                draining_by_group[group.name] = (
                    draining_by_group.get(group.name, 0) + 1
                )
        # nodes that vanished under us
        self._draining &= live
        for name in set(self._low_util_streak) - live:
            del self._low_util_streak[name]
        for name in list(self._draining):
            self._drain_one(name)

    def _node_group_name(self, node_name: str) -> str:
        ni = self.scheduler.cache.get_node_info(node_name)
        if ni is None or ni.node is None:
            return ""
        return ni.node.metadata.labels.get(LABEL_NODEGROUP, "")

    def _try_cordon(self, node: v1.Node, ni) -> bool:
        """Returns True iff the node was cordoned (now draining)."""
        name = node.metadata.name
        resident = list(ni.pods)
        unmovable = [p for p in resident if not self._movable(p)]
        if unmovable:
            metrics.inc(COUNTER_BLOCKED, {"reason": "unmovable_pods"})
            self._low_util_streak.pop(name, None)
            return False
        verdict = simulate_drain(self.sim, name, resident)
        if not verdict.ok:
            # the zero-eviction guarantee: a failed what-if means this
            # node is load-bearing — do NOT cordon, do NOT evict
            metrics.inc(COUNTER_BLOCKED, {"reason": "simulation_infeasible"})
            logger.info(
                "scale-down of %s blocked: %s", name, verdict.reason
            )
            self._low_util_streak.pop(name, None)
            return False

        def cordon(n):
            if n.spec.unschedulable:
                return None
            n.spec.unschedulable = True
            n.metadata.annotations[ANN_SCALE_DOWN] = "true"
            return n

        try:
            self.server.guaranteed_update("nodes", "", name, cordon)
        except NotFound:
            return False
        except (DegradedWrites, NotPrimary):
            metrics.inc(COUNTER_STORE_SKIPS, {"write": "cordon"})
            return False
        logger.info(
            "scale-down: cordoned %s (drain simulation re-placed %d pods)",
            name, verdict.replaced,
        )
        self._low_util_streak.pop(name, None)
        self._draining.add(name)
        return True

    def _drain_one(self, name: str) -> None:
        cache = self.scheduler.cache
        ni = cache.get_node_info(name)
        if ni is None:
            self._draining.discard(name)
            return
        victims = [
            p
            for p in ni.pods
            if not any(
                r.kind == "DaemonSet" for r in p.metadata.owner_references
            )
        ]
        if not victims:
            self._delete_node(name)
            return
        # re-verify MOVABILITY before every eviction wave, not just at
        # cordon time: a bare pod that landed after the cordon (in-flight
        # bind, direct node_name create) has nothing to recreate it —
        # deleting it would be permanent workload loss
        unmovable = [p for p in victims if not self._movable(p)]
        if unmovable:
            metrics.inc(COUNTER_BLOCKED, {"reason": "unmovable_pods"})
            logger.warning(
                "drain of %s paused: unmovable pod(s) %s arrived after "
                "the cordon", name,
                [p.metadata.key for p in unmovable],
            )
            return
        # re-verify feasibility too: the cluster may have changed since
        # the cordon, and evicting a pod the CURRENT what-if can't
        # re-place would break the zero-eviction guarantee — pause
        # (cordon stays, nothing evicted) and retry next pass
        verdict = simulate_drain(self.sim, name, victims)
        if not verdict.ok:
            metrics.inc(COUNTER_BLOCKED, {"reason": "drain_paused"})
            logger.warning(
                "drain of %s paused: %s", name, verdict.reason
            )
            return
        for pod in victims:
            if not self.limiter.try_acquire(actor="autoscaler"):
                return  # token bucket dry: resume next pass
            try:
                self.server.delete(
                    "pods", pod.metadata.namespace, pod.metadata.name
                )
                metrics.inc(COUNTER_EVICTIONS)
            except NotFound:
                pass
            except (DegradedWrites, NotPrimary):
                metrics.inc(COUNTER_STORE_SKIPS, {"write": "evict"})
                return

    def _delete_node(self, name: str) -> None:
        group = self.catalog.group(self._node_group_name(name))
        try:
            self.server.delete("nodes", "", name)
        except NotFound:
            pass
        except (DegradedWrites, NotPrimary):
            metrics.inc(COUNTER_STORE_SKIPS, {"write": "node_delete"})
            return
        self._draining.discard(name)
        gname = group.name if group else "unknown"
        if group is not None and group.deprovision is not None:
            try:
                group.deprovision(name)
            except Exception:
                logger.exception("deprovision hook failed for %s", name)
        metrics.inc(COUNTER_REMOVED, {"group": gname})
        logger.info("scale-down: removed empty node %s (group %s)", name, gname)


def autoscaler_health_lines() -> List[str]:
    """Autoscaler gauges/counters + simulation p99 rendered for the
    SIGUSR2 debugger dump (scheduler/cache/debugger.py) — a wedged
    scale-up (pods pending, nodes stuck registering) or a blocked
    scale-down is diagnosable from one signal. Empty when no autoscaler
    has published state in this process."""
    lines: List[str] = []
    for series in (
        metrics.snapshot_gauges("autoscaler_"),
        metrics.snapshot_counters("autoscaler_"),
    ):
        for name, labels, value in series:
            lines.append(metrics.format_series_line(name, labels, value))
    h = metrics.histogram(HIST_SIMULATION)
    if h is not None and h.n:
        p50, p99 = h.quantiles((0.5, 0.99))
        lines.append(
            f"  {HIST_SIMULATION}: n={h.n} p50={p50 * 1e3:.1f}ms "
            f"p99={p99 * 1e3:.1f}ms"
        )
    return lines
