"""Scale-up / scale-down planning as batched kernel what-if evaluation.

Upstream's cluster-autoscaler re-implements the scheduler's filter plugins
to simulate placements (simulator/ in the CA repo — a second copy of the
predicates that must be kept in lockstep by hand). Here the simulation IS
the production lattice kernel (ops/lattice.make_schedule_batch) run against
a what-if overlay of the HBM snapshot (ops/encoding.whatif_overlay):

* **Scale-up**: all pending pods are batch-evaluated in ONE kernel pass
  against real rows + K virtual rows per candidate shape (the NodeGroup
  catalog). The kernel's serial scan carry is the bin-packer: each placed
  pod's occupancy is visible to the next pod's decision, and a
  MostAllocated-weighted score greedily fills the fewest virtual nodes.
  Only virtual rows the kernel actually chose are provisioned.

* **Scale-down**: an underutilized node is drained only if a what-if pass
  with that node's row masked invalid proves EVERY resident pod re-places
  somewhere feasible (the zero-eviction guarantee: a failed simulation
  blocks the drain, it never "tries anyway").

Pods whose spec overflows the static device encoding (eb.fallback) are
bin-packed host-side with the scheduler's own filter plugins (the
`host_filter` callable wraps framework filters — still no duplicated
plugin logic).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..api import objects as v1
from ..ops.batch import encode_pod_batch
from ..ops.lattice import (
    NUM_SCORE_COMPONENTS,
    SC_COST,
    SC_MOST_ALLOC,
    SC_TAINT,
    make_schedule_batch,
)
from ..scheduler.cache.nodeinfo import NodeInfo
from ..utils.metrics import metrics
from .nodegroups import NodeGroup, NodeGroupCatalog

logger = logging.getLogger("kubernetes_tpu.autoscaler.planner")

HIST_SIMULATION = "autoscaler_simulation_duration_seconds"
COUNTER_SIMULATIONS = "autoscaler_simulation_passes_total"


def pack_weights(cost_aware: bool = False) -> np.ndarray:
    """Score weights for what-if passes. Feasibility is entirely the
    kernel's filter mask; the score only has to (a) PACK — MostAllocated
    funnels successive pods onto the fullest feasible node, so the scan
    carry greedily fills the fewest new nodes — and (b) prefer REAL rows:
    virtual rows carry a simulation-only PreferNoSchedule taint
    (VIRTUAL_BIAS_TAINT), and the dominant TaintToleration weight makes an
    existing feasible node always beat opening a fresh virtual one.

    cost_aware adds (c): CHEAPEST-feasible-shape packing — the cost
    column (normalized-inverted over the feasible set) sits between the
    real-row bias and the pack score, so among feasible virtual shapes
    the cheaper one wins and MostAllocated only breaks cost ties. With an
    unlabeled catalog the cost component is constant (inert), so
    cost-aware stays safe to leave on."""
    w = np.zeros(NUM_SCORE_COMPONENTS, np.float32)
    w[SC_MOST_ALLOC] = 1.0
    w[SC_TAINT] = 1000.0
    if cost_aware:
        w[SC_COST] = 10.0
    return w


# stamped on virtual rows INSIDE the simulation only (never on the
# provisioned node): PreferNoSchedule doesn't gate feasibility, but its
# intolerable-prefer count feeds the TaintToleration score, which is how
# "don't open a new node for a pod an existing node can hold" is expressed
# through the production kernel instead of a hand-rolled post-filter
VIRTUAL_BIAS_TAINT = v1.Taint(
    "autoscaler.kubernetes-tpu.io/virtual", "", v1.TAINT_PREFER_NO_SCHEDULE
)


@dataclass
class SimResult:
    """One kernel what-if pass, decoded."""

    chosen: np.ndarray  # [P] row index or -1 (row-aligned with pods)
    fallback: np.ndarray  # [P] bool — pod overflowed the static encoding
    virtual_rows: Dict[int, str]  # row -> virtual node name


@dataclass
class ScaleUpPlan:
    """Which virtual nodes the kernel actually used, per group."""

    nodes: Dict[str, List[str]] = field(default_factory=dict)  # group -> names
    placed: int = 0  # pods the simulation placed (real or virtual rows)
    unplaced: int = 0  # pods no shape in the catalog could hold
    truncated: int = 0  # pending pods past max_pods_per_pass (not simulated)
    capped: int = 0  # kernel-chosen nodes dropped by the per-cycle cap
    skipped: str = ""  # non-empty: why no simulation ran

    @property
    def total_nodes(self) -> int:
        return sum(len(v) for v in self.nodes.values())


class WhatIfSimulator:
    """Runs the production lattice kernel against snapshot overlays.

    Owns nothing but a PRNG key: state (encoder, masters, locks) stays in
    the scheduler cache, and every pass encodes under `cache.lock` exactly
    like the serial device path."""

    # three padded-batch buckets: every distinct pod-axis pad is an XLA
    # compile (seconds on CPU), but the serial scan's cost scales with the
    # PAD, not the live pod count — a 4x overshoot is a 4x slower pass,
    # so one middle bucket earns its compile
    PAD_BUCKETS = (64, 256)

    def __init__(self, cache: "SchedulerCache", hard_pod_affinity_weight: float = 1.0,
                 max_pods_per_pass: int = 1024, cost_aware: bool = True):
        self.cache = cache
        self.hard_w = hard_pod_affinity_weight
        self.max_pods = max_pods_per_pass
        self._rng = jax.random.PRNGKey(7)
        # cost_aware: cheapest-feasible-shape packing through the cost
        # column (inert on unlabeled fleets); False = pure MostAllocated
        # (the pre-ISSUE-15 behavior, kept for A/B benches)
        self._weights = pack_weights(cost_aware=cost_aware)

    def _pad(self, n: int) -> int:
        for b in self.PAD_BUCKETS:
            if n <= b < self.max_pods:
                return b
        return self.max_pods

    def simulate(
        self,
        pods: List[v1.Pod],
        virtual_nodes: List[v1.Node],
        mask_node: Optional[str] = None,
        kind: str = "scale_up",
        mask_nodes: Optional[List[str]] = None,
    ) -> Optional[SimResult]:
        """One what-if pass: pods × (real + virtual − masked) rows through
        the production kernel. None when the overlay has no room or a
        masked node is unknown. mask_nodes masks SEVERAL rows at once
        (the descheduler's evict-set simulation — whatif_overlay always
        took a row list; mask_node stays as the single-node spelling the
        scale-down path uses)."""
        pods = pods[: self.max_pods]
        if virtual_nodes:
            biased = []
            for n in virtual_nodes:
                c = n.deep_copy()
                c.spec.taints = list(c.spec.taints) + [VIRTUAL_BIAS_TAINT]
                biased.append(c)
            virtual_nodes = biased
        masked_names = list(mask_nodes or [])
        if mask_node is not None:
            masked_names.append(mask_node)
        t0 = time.monotonic()
        with self.cache.lock:
            enc = self.cache.encoder
            mask_rows: List[int] = []
            for mn in masked_names:
                r = enc.row_of(mn)
                if r < 0:
                    return None
                mask_rows.append(r)
            # encode FIRST: predicate/eterm interning can grow capacities,
            # which must settle before the overlay snapshot is built
            eb = encode_pod_batch(enc, pods, pad_to=self._pad(len(pods)))
            ov = enc.whatif_overlay(virtual_nodes, mask_rows)
            if ov is None:
                return None
            snap, vrows = ov
            v_cap = enc.cfg.v_cap
        virtual_map = {
            row: node.metadata.name
            for node, row in zip(virtual_nodes, vrows)
        }
        # the overlay snapshot shares no buffers with the live one (built
        # by the alias-free scatter under a generation pin), so the
        # (non-donating) kernel run needs no lease at all — it may overlap
        # wave launches and audits freely
        kern = make_schedule_batch(v_cap, self.hard_w)
        self._rng, sub = jax.random.split(self._rng)
        res = kern(snap, eb.batch, self._weights, sub)
        chosen = np.asarray(jax.device_get(res.chosen))
        metrics.inc(COUNTER_SIMULATIONS, {"kind": kind})
        metrics.observe(HIST_SIMULATION, time.monotonic() - t0)
        return SimResult(
            chosen=chosen[: len(pods)],
            fallback=np.asarray(eb.fallback)[: len(pods)],
            virtual_rows=virtual_map,
        )


def plan_scale_up(
    sim: WhatIfSimulator,
    catalog: NodeGroupCatalog,
    pending: List[v1.Pod],
    sizes: Dict[str, int],
    live_names: set,
    max_provision_per_cycle: int = 16,
    host_filter: Optional[Callable[[v1.Pod, NodeInfo], bool]] = None,
) -> ScaleUpPlan:
    """One scale-up planning pass: K virtual rows per group with headroom,
    one kernel pass over all pending pods, provision exactly the virtual
    rows the kernel chose."""
    plan = ScaleUpPlan()
    if not pending:
        plan.skipped = "no pending pods"
        return plan
    virtual_nodes: List[v1.Node] = []
    slot_group: Dict[str, NodeGroup] = {}
    taken = set(live_names)
    for g in catalog.groups:
        headroom = max(0, g.max_size - sizes.get(g.name, 0))
        k = min(headroom, max_provision_per_cycle, len(pending))
        for i in range(k):
            # STABLE slot names, reused every pass: the virtual hostname
            # pseudo-label is interned into the live vocab by the overlay
            # encode, and a fresh name per candidate per pass would leak
            # vocab entries until v_cap grows — which recompiles BOTH the
            # simulator and the production kernel (their cache keys embed
            # v_cap). Real (unique) names are minted below only for slots
            # the kernel actually chose.
            name = f"whatif.{g.name}.{i}"
            virtual_nodes.append(g.make_node(name))
            slot_group[name] = g
    if not virtual_nodes:
        plan.skipped = "every group at max_size"
        return plan
    res = sim.simulate(pending, virtual_nodes, kind="scale_up")
    if res is None:
        plan.skipped = "no snapshot capacity for virtual rows"
        return plan
    plan.truncated = max(0, len(pending) - len(res.chosen))
    used: Dict[str, List[str]] = {}  # group -> chosen slot names
    fallback_pods: List[v1.Pod] = []
    for i, pod in enumerate(pending[: len(res.chosen)]):
        if res.fallback[i]:
            fallback_pods.append(pod)
            continue
        row = int(res.chosen[i])
        if row < 0:
            plan.unplaced += 1
            continue
        plan.placed += 1
        vname = res.virtual_rows.get(row)
        if vname is not None:
            used.setdefault(slot_group[vname].name, [])
            if vname not in used[slot_group[vname].name]:
                used[slot_group[vname].name].append(vname)
    # pods past the static encoding: host-side first-fit with the
    # scheduler's OWN filter plugins (host_filter), onto fresh bins
    bins: List[Tuple[NodeGroup, str, NodeInfo]] = []
    if fallback_pods and host_filter is not None:
        for pod in fallback_pods:
            placed = False
            for _g, _name, ni in bins:
                if host_filter(pod, ni):
                    moved = pod.deep_copy()
                    moved.spec.node_name = ni.node.metadata.name
                    ni.add_pod(moved)
                    placed = True
                    break
            if not placed:
                for g in catalog.groups:
                    planned = len(used.get(g.name, ()))
                    opened = sum(1 for b in bins if b[0] is g)
                    if (
                        sizes.get(g.name, 0) + planned + opened
                        >= g.max_size
                    ):
                        continue
                    name = g.next_name(taken)
                    ni = NodeInfo(g.make_node(name))
                    if host_filter(pod, ni):
                        taken.add(name)
                        moved = pod.deep_copy()
                        moved.spec.node_name = name
                        ni.add_pod(moved)
                        bins.append((g, name, ni))
                        placed = True
                        break
            if placed:
                plan.placed += 1
            else:
                plan.unplaced += 1
    elif fallback_pods:
        plan.unplaced += len(fallback_pods)
    # mint REAL (unique) node names for exactly the slots the kernel used
    # (plus the fallback host bins, which already carry real names), then
    # enforce the cycle-global provisioning cap: per-group K bounds the
    # overlay width, but a mixed-shape burst could otherwise provision
    # groups×K nodes in one pass
    nodes: Dict[str, List[str]] = {}
    for gname, slots in used.items():
        g = catalog.group(gname)
        nodes[gname] = [g.next_name(taken) for _ in slots]
        taken.update(nodes[gname])
    for g, name, _ni in bins:
        nodes.setdefault(g.name, []).append(name)
    total = 0
    for gname in list(nodes):
        keep: List[str] = []
        for n in nodes[gname]:
            if total < max_provision_per_cycle:
                keep.append(n)
                total += 1
            else:
                plan.capped += 1
        if keep:
            nodes[gname] = keep
        else:
            del nodes[gname]
    plan.nodes = nodes
    return plan


@dataclass
class DrainVerdict:
    ok: bool
    reason: str = ""
    replaced: int = 0  # resident pods the simulation re-placed


def simulate_drain_set(
    sim: WhatIfSimulator,
    node_names: List[str],
    resident: List[v1.Pod],
    kind: str = "scale_down",
) -> DrainVerdict:
    """Drain-set what-if: would every resident pod of the WHOLE set
    re-place with all of those rows masked out in one overlay? DaemonSet-
    owned pods are excluded (they die with their node by design). Any pod
    the kernel cannot represent OR cannot re-place fails the verdict —
    the caller must then NOT drain. The single-node scale-down path
    (simulate_drain) and the descheduler's multi-node consolidation plans
    share this exact verdict, so "is this eviction safe" has one answer."""
    movable = []
    for p in resident:
        if any(r.kind == "DaemonSet" for r in p.metadata.owner_references):
            continue
        # simulate the pod's RECREATION, not its current incarnation: the
        # bound copy carries spec.node_name, which would pin the kernel's
        # NodeName filter to exactly the row being masked out
        clone = p.deep_copy()
        clone.spec.node_name = ""
        movable.append(clone)
    if not movable:
        return DrainVerdict(ok=True, reason="no resident pods")
    if len(movable) > sim.max_pods:
        # simulate() truncates to max_pods_per_pass — a verdict that never
        # evaluated the tail pods must not authorize their eviction
        return DrainVerdict(
            ok=False,
            reason=(
                f"{len(movable)} resident pods exceed the simulation "
                f"width ({sim.max_pods})"
            ),
        )
    res = sim.simulate(movable, [], mask_nodes=list(node_names), kind=kind)
    if res is None:
        return DrainVerdict(ok=False, reason="node unknown to the snapshot")
    if bool(res.fallback.any()):
        # a pod outside the static encoding can't be what-if'd on device;
        # blocking is the conservative (zero-eviction) answer
        return DrainVerdict(
            ok=False, reason="resident pod overflows the device encoding"
        )
    unplaced = int((res.chosen < 0).sum())
    if unplaced:
        return DrainVerdict(
            ok=False,
            reason=f"{unplaced}/{len(movable)} resident pods do not re-place",
            replaced=len(movable) - unplaced,
        )
    return DrainVerdict(ok=True, replaced=len(movable))


def simulate_drain(
    sim: WhatIfSimulator,
    node_name: str,
    resident: List[v1.Pod],
) -> DrainVerdict:
    """Scale-down what-if for ONE node (the autoscaler's spelling of the
    shared drain-set verdict)."""
    return simulate_drain_set(sim, [node_name], resident)
