"""Lease-based leader election.

Equivalent of client-go tools/leaderelection/leaderelection.go:111 with the
same invariants (leaderelection.go:78-96): leaseDuration > renewDeadline >
retryPeriod; a candidate acquires the Lease record if it is unheld or
expired, renews every retry_period, and calls on_stopped_leading (fatal in
the scheduler) if it cannot renew within renew_deadline. The Lease record
lives in the in-memory API server under kind "leases", so HA semantics are
testable in-process.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.objects import ObjectMeta
from .apiserver import APIServer, AlreadyExists, Conflict, NotFound


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0
    kind: str = "Lease"


@dataclass
class LeaderElectionConfig:
    lock_name: str = "kube-scheduler"
    lock_namespace: str = "kube-system"
    identity: str = "scheduler-0"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0

    def validate(self) -> None:
        if not self.lease_duration > self.renew_deadline:
            raise ValueError("leaseDuration must be greater than renewDeadline")
        if not self.renew_deadline > self.retry_period * 1.2:
            raise ValueError("renewDeadline must be greater than retryPeriod*JitterFactor")


class LeaderElector:
    def __init__(
        self,
        server: APIServer,
        config: LeaderElectionConfig,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        config.validate()
        self._server = server
        self._cfg = config
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._clock = clock
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        self._observed_renew = 0.0

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        """Block: acquire, then start leading; return when leadership lost/stopped."""
        if not self._acquire():
            return
        started = threading.Thread(
            target=self._on_started, daemon=True, name="leading"
        )
        self._is_leader.set()
        started.start()
        self._renew_loop()
        self._is_leader.clear()
        if self._on_stopped:
            self._on_stopped()

    # -- internals ----------------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        now = self._clock()
        cfg = self._cfg
        try:
            lease = self._server.get("leases", cfg.lock_namespace, cfg.lock_name)
        except NotFound:
            lease = Lease(
                metadata=ObjectMeta(name=cfg.lock_name, namespace=cfg.lock_namespace),
                holder_identity=cfg.identity,
                lease_duration_seconds=cfg.lease_duration,
                acquire_time=now,
                renew_time=now,
            )
            try:
                self._server.create("leases", lease)
                return True
            except AlreadyExists:
                return False
        if (
            lease.holder_identity != cfg.identity
            and lease.renew_time + lease.lease_duration_seconds > now
        ):
            return False  # held by someone else and not expired
        if lease.holder_identity != cfg.identity:
            lease.lease_transitions += 1
            lease.acquire_time = now
        lease.holder_identity = cfg.identity
        lease.renew_time = now
        lease.lease_duration_seconds = cfg.lease_duration
        try:
            self._server.update("leases", lease)
            return True
        except (Conflict, NotFound):
            return False

    def _acquire(self) -> bool:
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                self._observed_renew = self._clock()
                return True
            self._stop.wait(self._cfg.retry_period)
        return False

    def _renew_loop(self) -> None:
        while not self._stop.is_set():
            deadline = self._observed_renew + self._cfg.renew_deadline
            renewed = False
            while self._clock() < deadline and not self._stop.is_set():
                if self._try_acquire_or_renew():
                    self._observed_renew = self._clock()
                    renewed = True
                    break
                self._stop.wait(self._cfg.retry_period)
            if not renewed:
                return  # leadership lost
            self._stop.wait(self._cfg.retry_period)
