"""Lease-based leader election.

Equivalent of client-go tools/leaderelection/leaderelection.go:111 with the
same invariants (leaderelection.go:78-96): leaseDuration > renewDeadline >
retryPeriod; a candidate acquires the Lease record if it is unheld or
expired, renews every retry_period, and calls on_stopped_leading (fatal in
the scheduler) if it cannot renew within renew_deadline. The Lease record
lives in the in-memory API server under kind "leases", so HA semantics are
testable in-process.

Scheduler-HA additions on top of the reference shape:

  * **release-on-stop** (leaderelection.go ReleaseOnCancel): a graceful
    ``stop()`` clears ``holder_identity`` and bumps ``lease_transitions``
    so the warm standby acquires immediately instead of waiting out
    ``lease_duration`` — the zero-downtime rolling-upgrade path. A crash
    (``crash()``, or the process dying) releases nothing, and the standby
    pays the lease wait.
  * **degraded-store tolerance**: a lease write refused with a retryable
    503 (``DegradedWrites``) or a replication fence (``NotPrimary``) is a
    counted renewal skip, not an exception escaping the renew loop — the
    holder keeps leading as long as a renewal lands within
    ``renew_deadline``, exactly like every other control-plane writer
    rides the PR-3 window out.
  * **fencing token** (``BindFence``): each leadership grant is identified
    by ``(identity, lease_transitions)``. Store writes that carry the
    token are rejected with ``LeaderFenced`` once a newer grant exists, so
    a paused ex-leader resuming after a standby promotion cannot land late
    binds (the zombie fence; see ``APIServer.bind_pods``).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.objects import ObjectMeta
from ..runtime.consensus import DegradedWrites
from ..utils.metrics import metrics
from .apiserver import AlreadyExists, APIServer, Conflict, NotFound, NotPrimary

logger = logging.getLogger("kubernetes_tpu.client.leaderelection")

# one leadership grant landed (fresh acquire or takeover, not a renewal)
COUNTER_ACQUISITIONS = "leader_election_acquisitions_total"
# graceful releases (holder cleared + transitions bumped on stop())
COUNTER_RELEASES = "leader_election_releases_total"
# lease writes skipped because the store was degraded / fenced: the holder
# keeps leading and retries within renew_deadline
COUNTER_DEGRADED_SKIPS = "leader_election_degraded_renew_skips_total"
# a leader whose local disk failed released its lease so a disk-healthy
# replica promotes inside retry-periods (the fail-stop step-down)
COUNTER_DISK_STEPDOWNS = "leader_election_disk_stepdowns_total"


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0
    kind: str = "Lease"


@dataclass(frozen=True)
class BindFence:
    """Fencing token for one leadership grant: store writes carrying it
    are valid only while the named lease is still held by ``identity`` at
    exactly ``transitions`` (any takeover — or a graceful release — bumps
    the transition count and invalidates every outstanding token)."""

    namespace: str
    name: str
    identity: str
    transitions: int


# the one wire format for the fence over REST: the /binding route reads
# this header, rebuilds the BindFence, and validates it against the lease
# under the same lock the bind applies under (apiserver/rest.py). JSON in
# a header keeps identity strings with arbitrary characters unambiguous
# (a positional "ns/name/id/transitions" format would split on a
# hostname's separators).
FENCE_HEADER = "X-Leadership-Fence"


def fence_header_value(fence: BindFence) -> str:
    """Serialize a fence for the REST ``X-Leadership-Fence`` header."""
    return json.dumps(
        {
            "namespace": fence.namespace,
            "name": fence.name,
            "identity": fence.identity,
            "transitions": fence.transitions,
        },
        separators=(",", ":"),
    )


def fence_from_header(value: str) -> BindFence:
    """Parse the REST fence header back into a BindFence. Raises
    ValueError on anything malformed (the route maps it to 400 — a bad
    fence must never silently degrade to an UNfenced bind)."""
    try:
        d = json.loads(value)
        return BindFence(
            namespace=str(d["namespace"]),
            name=str(d["name"]),
            identity=str(d["identity"]),
            transitions=int(d["transitions"]),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed leadership fence header: {e}") from None


def default_identity() -> str:
    """hostname_uuid, the reference's default id (leaderelection options:
    id = hostname + "_" + uuid). A CONSTANT default here would be a trap:
    two replicas launched without an explicit identity would each read
    the other's lease as their own, renew it, and BOTH lead — with
    mutually valid fence tokens, silently voiding the zombie fence."""
    import socket
    import uuid

    return f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"


@dataclass
class LeaderElectionConfig:
    lock_name: str = "kube-scheduler"
    lock_namespace: str = "kube-system"
    identity: str = field(default_factory=default_identity)
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    # ReleaseOnCancel: clear the lease on graceful stop so the standby
    # takes over without waiting out lease_duration
    release_on_cancel: bool = True

    def validate(self) -> None:
        if not self.lease_duration > self.renew_deadline:
            raise ValueError("leaseDuration must be greater than renewDeadline")
        if not self.renew_deadline > self.retry_period * 1.2:
            raise ValueError("renewDeadline must be greater than retryPeriod*JitterFactor")


class LeaderElector:
    def __init__(
        self,
        server: APIServer,
        config: LeaderElectionConfig,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        disk_health: Optional[Callable[[], bool]] = None,
    ):
        """`disk_health` (when given) gates leadership on local disk
        state — wire it to the local store's write gate, e.g.
        ``lambda: store.write_gate.disk_healthy``. A candidate with a
        failed disk refuses to acquire; a LEADER whose disk fails
        releases the lease immediately (not a passive renew-deadline
        lapse), so a disk-healthy standby promotes inside retry-periods.
        This is the cluster-level half of the WAL's fail-stop: the
        process cannot durably log, so it must not lead."""
        config.validate()
        self._server = server
        self._cfg = config
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._clock = clock
        self._disk_health = disk_health
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        self._observed_renew = 0.0
        self._release_on_stop = config.release_on_cancel
        # transitions observed at the last successful acquire/renew: the
        # fencing token for THIS grant (a takeover always bumps it)
        self._observed_transitions = 0

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def stop(self) -> None:
        """Graceful shutdown: stop renewing and (when release_on_cancel)
        release the lease so the standby promotes immediately."""
        self._stop.set()

    def crash(self) -> None:
        """Chaos/test helper: stop WITHOUT releasing — simulates leader
        death, where the standby must wait out the lease."""
        self._release_on_stop = False
        self._stop.set()

    def fence(self) -> BindFence:
        """Fencing token for the CURRENT leadership grant. Meaningful only
        after _try_acquire_or_renew succeeded (i.e. inside on_started)."""
        return BindFence(
            namespace=self._cfg.lock_namespace,
            name=self._cfg.lock_name,
            identity=self._cfg.identity,
            transitions=self._observed_transitions,
        )

    def run(self) -> None:
        """Block: acquire, then start leading; return when leadership lost/stopped."""
        if not self._acquire():
            return
        started = threading.Thread(
            target=self._on_started, daemon=True, name="leading"
        )
        self._is_leader.set()
        started.start()
        self._renew_loop()
        self._is_leader.clear()
        if self._stop.is_set() and self._release_on_stop:
            # graceful shutdown while still holding the lease: release it
            # (ReleaseOnCancel) — a rolling upgrade must not cost the
            # standby a full lease_duration wait
            self.release()
        if self._on_stopped:
            self._on_stopped()

    # -- internals ----------------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        now = self._clock()
        cfg = self._cfg
        try:
            lease = self._server.get("leases", cfg.lock_namespace, cfg.lock_name)
        except OSError:
            # REST transport failure (partition, refused connect, timeout —
            # urllib errors are OSError subclasses): indistinguishable from
            # a degraded store for leadership purposes. Counted skip; the
            # renew loop keeps leading within renew_deadline, exactly the
            # in-process degraded-store contract.
            metrics.inc(COUNTER_DEGRADED_SKIPS)
            return False
        except NotFound:
            lease = Lease(
                metadata=ObjectMeta(name=cfg.lock_name, namespace=cfg.lock_namespace),
                holder_identity=cfg.identity,
                lease_duration_seconds=cfg.lease_duration,
                acquire_time=now,
                renew_time=now,
            )
            try:
                self._server.create("leases", lease)
                self._observed_transitions = lease.lease_transitions
                return True
            except AlreadyExists:
                return False
            except (DegradedWrites, NotPrimary, OSError):
                # OSError covers REST transport failures (urllib errors):
                # same contract as a degraded store — counted skip
                metrics.inc(COUNTER_DEGRADED_SKIPS)
                return False
        expired = lease.renew_time + lease.lease_duration_seconds <= now
        if (
            lease.holder_identity  # a RELEASED lease ("" holder) is free now
            and lease.holder_identity != cfg.identity
            and not expired
        ):
            return False  # held by someone else and not expired
        if lease.holder_identity != cfg.identity or expired:
            # a NEW grant: takeover, released lease, or re-acquire after
            # expiry — even by the SAME identity. The same-identity case
            # matters: a replacement process reusing a static identity
            # (--leader-elect-identity, a pod name) must mint a FRESH
            # fence, or the paused old incarnation's token would still
            # validate and its late binds would pass the zombie fence. A
            # healthy holder can never hit the expired branch on a normal
            # renew: renew_deadline < lease_duration means it deposes
            # itself before its own lease can expire.
            lease.lease_transitions += 1
            lease.acquire_time = now
        lease.holder_identity = cfg.identity
        lease.renew_time = now
        lease.lease_duration_seconds = cfg.lease_duration
        try:
            self._server.update("leases", lease)
            self._observed_transitions = lease.lease_transitions
            return True
        except (Conflict, NotFound):
            return False
        except (DegradedWrites, NotPrimary, OSError):
            # degraded store mid-renew, or a REST transport failure (a
            # partitioned/unreachable API server raises urllib errors,
            # which are OSErrors): either way the 503/blip must not escape
            # as an exception (it would kill the renew thread and depose a
            # healthy leader instantly). Counted skip; the renew loop keeps
            # leading and retrying until renew_deadline decides.
            metrics.inc(COUNTER_DEGRADED_SKIPS)
            return False

    def release(self) -> bool:
        """Clear holder_identity + bump lease_transitions (the reference's
        Lock.Update with an emptied LeaderElectionRecord). Returns True when
        the lease was actually released by us."""
        cfg = self._cfg
        try:
            lease = self._server.get("leases", cfg.lock_namespace, cfg.lock_name)
        except NotFound:
            return False
        except OSError:
            # unreachable API server at shutdown: same as a degraded store
            # below — the standby waits out the lease like a crash
            metrics.inc(COUNTER_DEGRADED_SKIPS)
            return False
        if lease.holder_identity != cfg.identity:
            return False  # someone already took over: nothing to release
        lease.holder_identity = ""
        lease.lease_transitions += 1
        lease.renew_time = 0.0
        try:
            self._server.update("leases", lease)
        except (Conflict, NotFound):
            return False
        except (DegradedWrites, NotPrimary, OSError):
            # best-effort: a degraded store at shutdown means the standby
            # waits out the lease like a crash — counted, not raised
            metrics.inc(COUNTER_DEGRADED_SKIPS)
            return False
        metrics.inc(COUNTER_RELEASES)
        logger.info(
            "released leader lease %s/%s (transitions=%d)",
            cfg.lock_namespace, cfg.lock_name, lease.lease_transitions,
        )
        return True

    def _disk_healthy(self) -> bool:
        if self._disk_health is None:
            return True
        try:
            return bool(self._disk_health())
        except Exception:
            logger.exception("disk_health probe raised; treating as failed")
            return False

    def _acquire(self) -> bool:
        while not self._stop.is_set():
            if not self._disk_healthy():
                # a fail-stopped disk cannot durably log: never lead
                self._stop.wait(self._cfg.retry_period)
                continue
            if self._try_acquire_or_renew():
                self._observed_renew = self._clock()
                metrics.inc(COUNTER_ACQUISITIONS)
                return True
            self._stop.wait(self._cfg.retry_period)
        return False

    def _renew_loop(self) -> None:
        while not self._stop.is_set():
            if not self._disk_healthy():
                # fail-stop step-down: ACTIVELY release instead of letting
                # the lease lapse — the standby acquires on its next
                # retry_period poll, not after renew_deadline. The lease
                # store itself is still writable (it is the disk-healthy
                # quorum's store; only OUR replica's sink died).
                metrics.inc(COUNTER_DISK_STEPDOWNS)
                logger.error(
                    "local disk failed while leading: releasing lease "
                    "%s/%s so a disk-healthy replica can promote",
                    self._cfg.lock_namespace,
                    self._cfg.lock_name,
                )
                self.release()
                self._release_on_stop = False  # already released
                return  # leadership lost
            deadline = self._observed_renew + self._cfg.renew_deadline
            renewed = False
            while self._clock() < deadline and not self._stop.is_set():
                if self._try_acquire_or_renew():
                    self._observed_renew = self._clock()
                    renewed = True
                    break
                self._stop.wait(self._cfg.retry_period)
            if not renewed:
                return  # leadership lost
            self._stop.wait(self._cfg.retry_period)
