"""Cluster event recording.

Equivalent of client-go tools/events EventBroadcaster/EventRecorder
(wired for the scheduler at reference pkg/scheduler/profile/profile.go:85):
structured Events ("Scheduled", "FailedScheduling", scheduler.go:378,544)
written to the API store under kind "events", with same-event aggregation by
(object, reason) count.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..api.objects import ObjectMeta
from .apiserver import APIServer, NotFound


@dataclass
class ClusterEvent:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_key: str = ""
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    action: str = ""
    note: str = ""
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)
    kind: str = "Event"


class EventRecorder:
    def __init__(self, server: Optional[APIServer], component: str = "scheduler"):
        self._server = server
        self._component = component
        self._lock = threading.Lock()
        self._seq = 0

    def eventf(
        self,
        obj: Any,
        event_type: str,
        reason: str,
        action: str,
        note: str = "",
    ) -> None:
        if self._server is None:
            return
        key = obj.metadata.key if hasattr(obj, "metadata") else str(obj)
        agg_name = f"{key.replace('/', '.')}.{reason}"
        try:
            existing = self._server.get("events", "default", agg_name)
            existing.count += 1
            existing.last_timestamp = time.time()
            existing.note = note
            try:
                self._server.update("events", existing)
                return
            except Exception:
                return
        except NotFound:
            pass
        with self._lock:
            self._seq += 1
        ev = ClusterEvent(
            metadata=ObjectMeta(name=agg_name, namespace="default"),
            involved_kind=getattr(obj, "kind", ""),
            involved_key=key,
            type=event_type,
            reason=reason,
            action=action,
            note=note,
        )
        try:
            self._server.create("events", ev)
        except Exception:
            pass
