"""Cluster event recording.

Equivalent of client-go tools/events EventBroadcaster/EventRecorder
(wired for the scheduler at reference pkg/scheduler/profile/profile.go:85):
structured Events ("Scheduled", "FailedScheduling", scheduler.go:378,544)
written to the API store under kind "events", with same-event aggregation by
(object, reason) count.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..api.objects import ObjectMeta
from .apiserver import APIServer, NotFound


@dataclass
class ClusterEvent:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_key: str = ""
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    action: str = ""
    note: str = ""
    count: int = 1
    first_timestamp: float = field(default_factory=time.time)
    last_timestamp: float = field(default_factory=time.time)
    kind: str = "Event"


class EventRecorder:
    """Async, aggregating recorder (the reference's EventBroadcaster:
    recorders drop events into a buffered channel; a sink goroutine writes
    them, correlating duplicates). eventf is O(dict insert) on the caller —
    the measured synchronous version cost ~4 ms PER EVENT inside the bind
    hot loop, capping scheduler throughput at a few hundred pods/s all by
    itself. A daemon flusher drains the aggregation buffer and performs
    the API writes off the critical path."""

    def __init__(
        self,
        server: Optional[APIServer],
        component: str = "scheduler",
        max_buffer: int = 100_000,
    ):
        self._server = server
        self._component = component
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # (involved_key, reason) -> pending ClusterEvent (count accumulates)
        self._pending: Dict[tuple, ClusterEvent] = {}
        self._max_buffer = max_buffer
        self._dropped = 0
        self._stopped = False
        self._flusher: Optional[threading.Thread] = None
        self._inflight = False  # flusher is writing a drained batch

    def eventf(
        self,
        obj: Any,
        event_type: str,
        reason: str,
        action: str,
        note: str = "",
    ) -> None:
        if self._server is None:
            return
        key = obj.metadata.key if hasattr(obj, "metadata") else str(obj)
        now = time.time()
        drain = False
        with self._cond:
            agg = (key, reason)
            cur = self._pending.get(agg)
            if cur is not None:
                cur.count += 1
                cur.last_timestamp = now
                cur.note = note
            elif len(self._pending) >= self._max_buffer:
                self._dropped += 1  # overload: shed, never block callers
                return
            else:
                # built only on the miss path: the storm case (same
                # key+reason repeating) must stay allocation-free
                self._pending[agg] = ClusterEvent(
                    metadata=ObjectMeta(
                        name=f"{key.replace('/', '.')}.{reason}",
                        namespace="default",
                    ),
                    involved_kind=getattr(obj, "kind", ""),
                    involved_key=key,
                    type=event_type,
                    reason=reason,
                    action=action,
                    note=note,
                )
            if self._stopped:
                # flusher is gone (or finishing): drain inline through the
                # same swap protocol so stragglers serialize with it and
                # with each other — no unsynchronized read-modify-write
                drain = True
            else:
                if self._flusher is None:
                    self._flusher = threading.Thread(
                        target=self._flush_loop, daemon=True, name="event-flusher"
                    )
                    self._flusher.start()
                self._cond.notify()
        if drain:
            self._drain()

    def _write_batch(self, batch: Dict[tuple, ClusterEvent]) -> None:
        """One drained batch → the store. Prefers the bulk ownership-
        transfer sink (one lock, no defensive copies — the recorder never
        touches handed-over objects again); REST-shaped servers without it
        get the per-event upsert."""
        bulk = getattr(self._server, "write_events_bulk", None)
        if bulk is not None:
            try:
                bulk(list(batch.values()))
            except Exception:
                # NO per-event fallback here: the bulk apply mutates the
                # store before its WAL append, so a late failure may have
                # already committed the counts in memory — re-applying
                # per-event would double them. Events are best-effort;
                # drop the batch instead.
                pass
            return
        for ev in batch.values():
            self._write(ev)

    def _drain(self) -> None:
        """Write everything pending using the swap/_inflight protocol
        (shared with the flusher thread)."""
        while True:
            with self._cond:
                while self._inflight:
                    self._cond.wait(timeout=1.0)
                if not self._pending:
                    return
                batch = self._pending
                self._pending = {}
                self._inflight = True
            try:
                self._write_batch(batch)
            finally:
                with self._cond:
                    self._inflight = False
                    self._cond.notify_all()

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                # sleep while a straggler drain owns the swap, or while
                # there is nothing to do and we're not stopping
                while self._inflight or (
                    not self._pending and not self._stopped
                ):
                    self._cond.wait(timeout=1.0)
                if not self._pending:
                    return  # stopped and drained
                batch = self._pending
                self._pending = {}
                self._inflight = True
            try:
                self._write_batch(batch)
            finally:
                with self._cond:
                    self._inflight = False
                    self._cond.notify_all()

    def _write(self, ev: ClusterEvent) -> None:
        try:
            existing = self._server.get(
                "events", ev.metadata.namespace, ev.metadata.name
            )
            existing.count += ev.count
            existing.last_timestamp = ev.last_timestamp
            existing.note = ev.note
            try:
                self._server.update("events", existing, check_version=False)
            except Exception:
                pass
        except NotFound:
            try:
                self._server.create("events", ev)
            except Exception:
                pass
        except Exception:
            pass

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until everything recorded so far is written (tests,
        shutdown). Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
