"""Client machinery: in-memory API server, informers, workqueue, leader election.

The functional equivalent of the reference's kube-apiserver + client-go for
in-process topologies (the same shape its integration tests use: real event
pipeline, no network). The APIServer is the storage/watch layer
(etcd3 store + watch cacher collapsed into one versioned in-memory store);
informers replay its watch streams into local Indexers and user handlers.
"""

from .apiserver import APIServer, Conflict, NotFound, AlreadyExists  # noqa: F401
from .informers import SharedInformer, SharedInformerFactory  # noqa: F401
from .workqueue import (  # noqa: F401
    RateLimitingQueue,
    ExponentialBackoffRateLimiter,
    parallelize_until,
)
from .leaderelection import LeaderElector, LeaderElectionConfig  # noqa: F401
from .events import EventRecorder, ClusterEvent  # noqa: F401
