"""Shared informers: list+watch replay into local indexers and handlers.

Equivalent of client-go's Reflector (tools/cache/reflector.go:210
ListAndWatch) + DeltaFIFO + sharedIndexInformer (shared_informer.go), with
the simplification the in-process store allows: the watch stream is lossless
and ordered, so the delta queue collapses into direct dispatch on the
informer thread. Handlers see the same contract: OnAdd/OnUpdate/OnDelete
after an initial synthetic Add per listed object, HasSynced after the initial
list is delivered.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional

from ..runtime.store import Indexer, IndexFunc
from ..runtime.watch import ADDED, BOOKMARK, DELETED, MODIFIED
from ..utils.metrics import metrics

from .apiserver import APIServer, Expired

logger = logging.getLogger("kubernetes_tpu.client.informers")

# relist backoff for the ListAndWatch restart loop: grows on consecutive
# failures (Expired/410, list errors, watch streams dying at birth), resets
# to the floor once a re-established watch actually delivers an event
RELIST_BACKOFF_INITIAL = 0.05
RELIST_BACKOFF_CAP = 5.0
COUNTER_RELISTS = "informer_relists_total"  # labels: kind, reason
# bookmark events consumed (resume position advanced, no handlers invoked)
COUNTER_BOOKMARKS = "informer_bookmarks_total"  # labels: kind
# watch streams resumed at last_resource_version WITHOUT a re-list (the
# watch-cache window absorbed the flap)
COUNTER_RESUMES = "informer_watch_resumes_total"  # labels: kind


class ResourceEventHandler:
    """Duck-typed handler; subclass or pass callables to SharedInformer.add_handler."""

    def on_add(self, obj: Any) -> None:  # pragma: no cover - interface
        pass

    def on_update(self, old: Any, new: Any) -> None:  # pragma: no cover
        pass

    def on_delete(self, obj: Any) -> None:  # pragma: no cover
        pass


class _FuncHandler(ResourceEventHandler):
    def __init__(self, on_add=None, on_update=None, on_delete=None, filter_fn=None):
        self._add, self._update, self._delete = on_add, on_update, on_delete
        self._filter = filter_fn

    def on_add(self, obj):
        if self._add and (self._filter is None or self._filter(obj)):
            self._add(obj)

    def on_update(self, old, new):
        if self._filter is None:
            if self._update:
                self._update(old, new)
            return
        # FilteringResourceEventHandler semantics (client-go shared_informer):
        # filter old and new independently; add/delete on transition.
        old_ok = self._filter(old)
        new_ok = self._filter(new)
        if old_ok and new_ok:
            if self._update:
                self._update(old, new)
        elif not old_ok and new_ok and self._add:
            self._add(new)
        elif old_ok and not new_ok and self._delete:
            self._delete(old)

    def on_delete(self, obj):
        if self._delete and (self._filter is None or self._filter(obj)):
            self._delete(obj)


class SharedInformer:
    def __init__(
        self,
        server: APIServer,
        kind: str,
        indexers: Optional[Dict[str, IndexFunc]] = None,
    ):
        self.kind = kind
        self._server = server
        self.indexer = Indexer(indexers=indexers)
        self._handlers: List[ResourceEventHandler] = []
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watcher = None
        self._relist_backoff = RELIST_BACKOFF_INITIAL
        # resume position: the rv of the last event (or bookmark) this
        # informer has fully processed. A dying watch stream reconnects
        # HERE instead of re-listing; only a true 410 — the watch cache
        # evicted events past this position — forces the relist.
        self.last_resource_version = 0
        self._resume = False  # True: skip the list, watch from last rv

    def add_handler(
        self,
        on_add: Optional[Callable[[Any], None]] = None,
        on_update: Optional[Callable[[Any, Any], None]] = None,
        on_delete: Optional[Callable[[Any], None]] = None,
        filter_fn: Optional[Callable[[Any], bool]] = None,
    ) -> None:
        self._handlers.append(_FuncHandler(on_add, on_update, on_delete, filter_fn))

    def add_event_handler(self, handler: ResourceEventHandler) -> None:
        self._handlers.append(handler)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def _replace(self, objs) -> None:
        """Replace-semantics sync (the reflector's DeltaFIFO Replace):
        DELETE + on_delete anything the indexer holds that the list no
        longer contains (a plain upsert replay would leave ghosts for
        objects deleted during a watch gap), on_update for keys already
        known, on_add only for genuinely new ones — a relist must not
        replay the world as adds: add handlers legitimately treat an add
        as new state (queue re-activation, cache accounting), and a
        flapping watch would hammer them with the full object set per
        flap. The filtering handler wrapper turns updates that cross its
        predicate into the right add/delete, so objects that changed
        sides during the gap still land correctly."""
        listed = {o.metadata.key for o in objs}
        for stale_key in [
            k for k in (o.metadata.key for o in self.indexer.list())
            if k not in listed
        ]:
            gone = self.indexer.get(stale_key)
            if gone is None:
                continue
            self.indexer.delete(gone)
            for h in self._handlers:
                h.on_delete(gone)
        for obj in objs:
            old = self.indexer.get(obj.metadata.key)
            self.indexer.add(obj)
            if old is None:
                for h in self._handlers:
                    h.on_add(obj)
            else:
                for h in self._handlers:
                    h.on_update(old, obj)

    def _sleep_backoff(self) -> bool:
        """Sleep the current backoff and grow it. True when stopping."""
        if self._stop.wait(self._relist_backoff):
            return True
        self._relist_backoff = min(self._relist_backoff * 2, RELIST_BACKOFF_CAP)
        return False

    def _backoff_failure(self, reason: str) -> bool:
        """Count one relist cause, sleep the current backoff, grow it.
        Returns True when the informer is stopping."""
        metrics.inc(COUNTER_RELISTS, {"kind": self.kind, "reason": reason})
        return self._sleep_backoff()

    def _advance_rv(self, rv: int) -> None:
        if rv > self.last_resource_version:
            self.last_resource_version = rv

    def _run(self) -> None:
        """The reflector's ListAndWatch restart loop, watch-cache aware:
        list (Replace semantics) → watch from the list rv → dispatch until
        the stream dies → RESUME the watch at last_resource_version. Every
        failure mode re-enters the loop instead of killing the informer
        thread:

          * list errors (transient 401/5xx) retry with backoff
          * Expired at the list rv ("resourceVersion too old" between the
            list and the first watch): re-list (reason=expired)
          * a watch stream that closes WITHOUT stop() (flapping
            connection, REST stream death): reconnect at the last seen rv
            — the watch cache replays the gap from its event window, so a
            flap costs NO re-list and NO handler churn
          * Expired on a RESUME attempt (a true 410-outside-window — the
            cache evicted events past our position): re-list with Replace
            semantics (reason=window_expired)

        BOOKMARK events advance last_resource_version WITHOUT invoking
        handlers, so an informer on a quiet selector still rides inside
        the replay window. The shared backoff grows across consecutive
        failures and resets to the floor once a re-established watch
        delivers an event (bookmarks count — they prove the stream)."""
        while not self._stop.is_set():
            fresh_list = False
            if not self._resume or not self.last_resource_version:
                try:
                    objs, rv = self._server.list(self.kind)  # graftlint: allow-blocking(the pump's own re-list; only this informer's handlers wait)
                except Exception:
                    logger.exception("list of %s failed; retrying", self.kind)
                    if self._backoff_failure("list-error"):
                        return
                    continue
                self._replace(objs)
                self._synced.set()
                self._advance_rv(rv)
                fresh_list = True
            self._resume = False
            try:
                self._watcher = self._server.watch(  # graftlint: allow-blocking(re-arming this informer's own stream)
                    self.kind, from_version=self.last_resource_version
                )
            except Expired:
                if fresh_list:
                    # the gap opened between our list and the watch —
                    # the historical relist cause
                    logger.warning(
                        "watch for %s expired at rv %d; re-listing",
                        self.kind,
                        self.last_resource_version,
                    )
                    reason = "expired"
                else:
                    # resume position fell out of the watch-cache window:
                    # the one case that still costs a full re-list
                    logger.warning(
                        "watch resume for %s at rv %d outside the cache "
                        "window; re-listing",
                        self.kind,
                        self.last_resource_version,
                    )
                    reason = "window_expired"
                if self._backoff_failure(reason):
                    return
                continue
            if not fresh_list:
                metrics.inc(COUNTER_RESUMES, {"kind": self.kind})
            delivered = False
            for ev in self._watcher:
                if self._stop.is_set():
                    return
                if not delivered:
                    delivered = True
                    self._relist_backoff = RELIST_BACKOFF_INITIAL
                if ev.type == BOOKMARK:
                    metrics.inc(COUNTER_BOOKMARKS, {"kind": self.kind})
                    self._advance_rv(
                        ev.resource_version
                        or getattr(
                            ev.object.metadata, "resource_version", 0
                        )
                    )
                    continue
                key = ev.object.metadata.key
                if ev.type == ADDED:
                    self.indexer.add(ev.object)
                    for h in self._handlers:
                        h.on_add(ev.object)
                elif ev.type == MODIFIED:
                    old = self.indexer.get(key)
                    self.indexer.update(ev.object)
                    for h in self._handlers:
                        h.on_update(old, ev.object)
                elif ev.type == DELETED:
                    self.indexer.delete(ev.object)
                    for h in self._handlers:
                        h.on_delete(ev.object)
                self._advance_rv(
                    ev.resource_version
                    or ev.object.metadata.resource_version
                    or 0
                )
            if self._stop.is_set():
                return
            # stream closed under us (watch flap): resume at the last rv —
            # the cache window makes reconnects cheap; a true 410 on the
            # reconnect falls into the window_expired relist above
            self._resume = True
            if self._sleep_backoff():
                return

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.stop()

    # Lister surface
    def list(self) -> List[Any]:
        return self.indexer.list()

    def get(self, key: str) -> Optional[Any]:
        return self.indexer.get(key)


class SharedInformerFactory:
    """informers.NewSharedInformerFactory: one informer per kind, shared."""

    def __init__(self, server: APIServer):
        self._server = server
        self._informers: Dict[str, SharedInformer] = {}
        self._lock = threading.Lock()

    def informer(
        self, kind: str, indexers: Optional[Dict[str, IndexFunc]] = None
    ) -> SharedInformer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = SharedInformer(self._server, kind, indexers)
                self._informers[kind] = inf
            return inf

    def start(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        with self._lock:
            informers = list(self._informers.values())
        return all(inf.wait_for_sync(timeout) for inf in informers)

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.stop()
