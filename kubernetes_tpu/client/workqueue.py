"""Work queues and bounded parallel helpers.

Equivalent of client-go util/workqueue: de-duplicating work queue with
rate-limited re-adds (default_rate_limiters.go ItemExponentialFailureRateLimiter)
and ParallelizeUntil (parallelizer.go:30) — the reference's 16-goroutine
fan-out that the TPU build replaces on the hot path but still uses for
host-side controllers.
"""

from __future__ import annotations

import heapq
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional


class ExponentialBackoffRateLimiter:
    """per-item exponential backoff: base * 2^failures, capped."""

    def __init__(self, base: float = 0.005, cap: float = 1000.0):
        self._base = base
        self._cap = cap
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self._base * (2**n), self._cap)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class RateLimitingQueue:
    """Deduplicating FIFO with delayed adds and dirty/processing sets.

    Semantics match workqueue.Type: an item added while being processed is
    re-queued when Done is called; duplicate adds coalesce.
    """

    def __init__(self, rate_limiter: Optional[ExponentialBackoffRateLimiter] = None):
        self._cond = threading.Condition()
        self._queue: List[Any] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutdown = False
        self._limiter = rate_limiter or ExponentialBackoffRateLimiter()
        # delayed adds: heap of (ready_time, seq, item)
        self._delayed: List = []
        self._seq = 0
        self._delay_thread = threading.Thread(
            target=self._delay_loop, daemon=True, name="workqueue-delay"
        )
        self._delay_thread.start()

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Any) -> None:
        self.add_after(item, self._limiter.when(item))

    def forget(self, item: Any) -> None:
        self._limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self._limiter.num_requeues(item)

    def _delay_loop(self) -> None:
        while True:
            with self._cond:
                if self._shutdown:
                    return
                now = time.monotonic()
                while self._delayed and self._delayed[0][0] <= now:
                    _, _, item = heapq.heappop(self._delayed)
                    if item not in self._dirty:
                        self._dirty.add(item)
                        if item not in self._processing:
                            self._queue.append(item)
                            self._cond.notify()
                timeout = (
                    max(0.0, self._delayed[0][0] - now) if self._delayed else 0.05
                )
            time.sleep(min(timeout, 0.05))

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue and not self._shutdown:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._shutdown and not self._queue:
                return None
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)


def parallelize_until(
    workers: int, pieces: int, do_work: Callable[[int], None]
) -> None:
    """workqueue.ParallelizeUntil: chunked fan-out of `pieces` index calls."""
    if pieces == 0:
        return
    workers = max(1, min(workers, pieces))
    if workers == 1:
        for i in range(pieces):
            do_work(i)
        return
    with ThreadPoolExecutor(max_workers=workers) as pool:
        list(pool.map(do_work, range(pieces)))
