"""Versioned in-memory API store with watch fan-out.

Collapses the reference's persistence stack — etcd (gRPC) + etcd3 store
(staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go) + watch cacher
(storage/cacher/cacher.go:448) — into one process-local component with the
same observable semantics the control plane depends on:

  * monotonically increasing resourceVersion per write
  * optimistic concurrency: update conflicts on stale resource_version
  * list + watch-from-version with ordered event delivery per watcher
  * per-(kind, namespace) keying

Components talk to it through plain method calls instead of REST; the handler
chain (authn/authz/admission) is represented by pluggable admit hooks.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization, validation
from ..api.objects import event_copy
from ..runtime.watch import ADDED, DELETED, MODIFIED, Event, Watcher
from ..testing.lockgraph import named_lock, track_attrs
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.apiserver")

# disk-health state the write gate acts on: 0 = ok, 1 = pressure
# (read-only, lifts with free space), 2 = failed (fail-stop, permanent)
GAUGE_DISK_STATE = "store_disk_state"
# recovery found mid-log corruption: serving the longest valid prefix,
# must resync from a healthy peer before leading
GAUGE_DISK_CORRUPT = "store_disk_corrupt"
COUNTER_PRESSURE_ENTRIES = "store_disk_pressure_entries_total"
COUNTER_COMPACT_FAILURES = "wal_compaction_failures_total"


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class TooManyRequests(ValueError):
    """Eviction blocked by a PodDisruptionBudget (HTTP 429, the registry's
    eviction.go DisruptionBudget error)."""


class Conflict(ValueError):
    """Stale resource_version on update (optimistic-concurrency failure)."""


class Expired(ValueError):
    """Watch resourceVersion older than retained history (HTTP 410 Gone;
    the reference's "The resourceVersion for the provided watch is too
    old" — watchers must re-list)."""


def list_and_watch(server, kind: str, seed) -> "Watcher":
    """list → seed(objs) → watch(list rv), retrying the whole pair on
    Expired (the reflector's ListAndWatch restart). seed must tolerate
    re-delivery (queue adds dedup; event handlers treat re-adds as
    updates)."""
    while True:
        objs, rv = server.list(kind)
        seed(objs)
        try:
            return server.watch(kind, from_version=rv)
        except Expired:
            continue


AdmitHook = Callable[[str, str, Any], None]  # (verb, kind, obj) -> raise to deny


class NotPrimary(RuntimeError):
    """Write rejected: this store was fenced by a higher replication term
    (a follower promoted; see runtime/replication.py)."""


class LeaderFenced(Conflict):
    """Write rejected: the caller's leadership lease was superseded — a
    newer holder (or a graceful release) bumped the lease transitions
    since the caller's fencing token was minted. The zombie-ex-leader
    fence: a paused leader resuming after a standby promotion gets THIS,
    never a silently applied late bind. Non-retryable by design (the
    caller is not the leader anymore)."""


class APIServer:
    def __init__(self, watch_history: int = 200000, wal=None):
        # named for the lock-order watchdog (testing/lockgraph.py)
        self._lock = named_lock("store")
        self._rv = 0
        # kind -> key -> object
        self._objects: Dict[str, Dict[str, Any]] = {}
        # kind -> list of live watchers
        self._watchers: Dict[str, List[Watcher]] = {}
        # kind -> ring buffer of past events for watch-from-version replay
        self._history: Dict[str, deque] = {}
        self._history_len = watch_history
        # kind -> rv of the newest event EVICTED from its ring (watch()'s
        # exact staleness check)
        self._evicted_rv: Dict[str, int] = {}
        self.admit_hooks: List[AdmitHook] = []
        # optional durability (runtime/wal.py): every mutation is logged
        # before acknowledgment; recover() rebuilds a server from disk —
        # the crash-only contract of the reference's etcd layer
        self._wal = wal
        self._compacting = threading.Event()
        self._compact_failures = 0
        self._compact_backoff_until = 0.0
        # recovery classified the WAL as mid-log corrupt: state is the
        # longest valid prefix; a corrupt replica must resync from a
        # healthy peer (replication snap/catchup) before it may lead
        self.disk_corrupt = False
        # optional low-watermark free-space probe (runtime/wal.py
        # DiskSpaceProbe): checked on the write-admission path so the
        # store enters disk-pressure read-only BEFORE appends hit ENOSPC
        # and auto-reopens once space recovers
        self.disk_probe = None
        if wal is not None and hasattr(wal, "on_disk_failed"):
            # the WAL poisons on any write/fsync error, from ANY append
            # site (mutations, consensus epoch records, compaction
            # reopen) — mirror it into the write gate immediately
            wal.on_disk_failed(self._on_wal_disk_failed)
        # optional HA (runtime/replication.py): mutations ship to followers
        # synchronously after the local WAL append. write_gate is the one
        # write-admission authority (runtime/store.py): read_only maps to
        # its higher-term fence; consensus mode also arms its degraded
        # (quorum-lost, 503-retryable) state through it
        self.replicator = None
        from ..runtime.store import WriteGate

        self.write_gate = WriteGate()
        # node name -> callable(pod_key, ...) -> str: the kubelet's log and
        # exec surfaces (kubectl logs/exec flow apiserver -> kubelet ->
        # runtime GetContainerLogs/ExecSync in the reference; node agent
        # pools register here)
        self.log_providers: Dict[str, Callable] = {}
        self.exec_providers: Dict[str, Callable] = {}

    @classmethod
    def recover(cls, wal_path: str, watch_history: int = 200000) -> "APIServer":
        """Rebuild a server from its WAL + snapshot (crash restart).
        Watch history does not survive (watchers must re-list, exactly like
        an etcd compaction forcing a reflector relist)."""
        from ..runtime.wal import WriteAheadLog

        report = WriteAheadLog.recover_report(wal_path)
        srv = cls(watch_history=watch_history, wal=WriteAheadLog(wal_path))
        srv._rv = report.rv
        srv._objects = report.objects
        if report.corrupt:
            # mid-log corruption: the state below is the longest valid
            # prefix, honest but possibly missing acked writes — flag it
            # so replication refuses to promote this replica until it has
            # resynced from a healthy peer (Follower disk_corrupt gate)
            srv.disk_corrupt = True
            metrics.set_gauge(GAUGE_DISK_CORRUPT, 1.0)
        return srv

    def _log(self, verb: str, kind: str, obj: Any) -> None:
        if self._wal is None and self.replicator is None:
            return
        self._log_batch([(self._rv, verb, kind, obj)])

    def _log_batch(self, records) -> None:
        """records: [(rv, verb, kind, obj)] — one group-committed append,
        then synchronous replication to any attached followers (ack'd
        before the mutation is acknowledged to the client: kill the
        primary at any point and no acknowledged write is lost)."""
        if not records:
            return
        if self._wal is not None:
            try:
                self._wal.append_batch(records)
            except OSError as e:
                # the record is NOT durable, so the client must not see an
                # ack — but the in-memory mutation already applied and is
                # READABLE, so watchers must still learn of it (same
                # reasoning as the ship() failure below). Then surface the
                # disk-classified degraded error: DiskPressure (ENOSPC,
                # retryable once space frees) or DiskFailed (sink
                # fail-stop; the write gate goes read-only for good).
                for rv, verb, kind, obj in records:
                    ev_type = {"create": ADDED, "delete": DELETED}.get(
                        verb, MODIFIED
                    )
                    self._notify(kind, Event(ev_type, copy.deepcopy(obj), rv))
                raise self._classify_disk_error(e) from e
            self._maybe_compact()
        if self.replicator is not None:
            try:
                self.replicator.ship(records)
            except Exception:
                # quorum loss (QuorumLost/NotPrimary) aborts the caller
                # BEFORE its own _notify — but the records stay applied,
                # WAL-durable, and READABLE (and may yet commit), so
                # watchers must still learn of them or every informer
                # desyncs from list() with a permanent rv gap in the
                # stream. Synthesize the events the caller would have
                # sent, then re-raise (the client still sees the 503).
                for rv, verb, kind, obj in records:
                    ev_type = {"create": ADDED, "delete": DELETED}.get(
                        verb, MODIFIED
                    )
                    self._notify(kind, Event(ev_type, copy.deepcopy(obj), rv))
                raise

    def _on_wal_disk_failed(self, why: str) -> None:
        """WAL fail-stop callback (fired under the wal lock: flag flips
        only, never call back into the WAL or take the store lock)."""
        self.write_gate.set_disk_failed(why)
        metrics.set_gauge(GAUGE_DISK_STATE, 2.0)

    def _classify_disk_error(self, e: OSError) -> Exception:
        """The fail-stop seam: every WAL-append OSError on the mutation
        path routes through here to flip the write gate and become the
        matching retryable DegradedWrites subclass."""
        from ..runtime.consensus import DiskFailed, DiskPressure
        from ..runtime.wal import DiskFull

        if isinstance(e, DiskFull):
            self._enter_disk_pressure(f"WAL append hit ENOSPC: {e}")
            return DiskPressure(str(e))
        self.write_gate.set_disk_failed(str(e))
        metrics.set_gauge(GAUGE_DISK_STATE, 2.0)
        return DiskFailed(
            f"WAL append failed; store is read-only (fail-stop): {e}"
        )

    def _enter_disk_pressure(self, why: str) -> None:
        self.write_gate.set_disk_pressure(True)
        metrics.inc(COUNTER_PRESSURE_ENTRIES)
        metrics.set_gauge(GAUGE_DISK_STATE, 1.0)
        logger.warning("store entering disk-pressure read-only: %s", why)
        if self.disk_probe is None and self._wal is not None:
            # nothing would ever clear the gate otherwise: arm a default
            # probe over the WAL volume so recovery is observed
            from ..runtime.wal import DiskSpaceProbe

            self.disk_probe = DiskSpaceProbe(self._wal.log_path)
        if self.disk_probe is not None:
            # sync the probe's hysteresis with the gate: an ENOSPC-driven
            # entry (quota exhaustion, a full volume the watermark never
            # saw coming) must still clear through the probe's recovery
            # transition — otherwise the gate sticks even after space
            # frees, because check() only reports a recovery AFTER an
            # observed entry
            self.disk_probe.under_pressure = True
        # compaction as reclaim: a snapshot + log rewrite usually SHRINKS
        # the volume (the log holds every record since the last snapshot)
        if self._wal is not None and not self._compacting.is_set():
            self._compacting.set()
            threading.Thread(
                target=self._compact_async, daemon=True, name="wal-reclaim"
            ).start()

    def _check_disk_pressure(self) -> None:
        """Write-admission-path probe: enter read-only BEFORE appends fail
        with ENOSPC; auto-reopen when free space recovers (the probe has
        hysteresis and rate-limits its own statvfs)."""
        probe = self.disk_probe
        if probe is None:
            return
        state = probe.check()
        if state is True and not self.write_gate.disk_pressure:
            self._enter_disk_pressure(
                f"free space below low watermark ({probe.low_bytes} B)"
            )
        elif state is False and self.write_gate.disk_pressure:
            self.write_gate.set_disk_pressure(False)
            if not self.write_gate.disk_failed:
                metrics.set_gauge(GAUGE_DISK_STATE, 0.0)
            logger.info("disk pressure cleared: store writable again")

    def _maybe_compact(self) -> None:
        if (
            self._wal.due()
            and not self._compacting.is_set()
            and time.monotonic() >= self._compact_backoff_until
        ):
            # compaction runs OFF the mutation path: serializing + fsyncing
            # the whole store under the server lock would stall every API
            # call for seconds at kubemark scale (the reference compacts in
            # a background goroutine for the same reason)
            self._compacting.set()
            threading.Thread(
                target=self._compact_async, daemon=True, name="wal-compact"
            ).start()

    def _compact_async(self) -> None:
        try:
            with self._lock:  # cheap structural copies only under the lock
                rv = self._rv
                objects = {
                    kind: [copy.deepcopy(o) for o in store.values()]
                    for kind, store in self._objects.items()
                }
            self._wal.write_snapshot(rv, objects)
            self._compact_failures = 0
        except OSError:
            # failed compaction must never wedge the append path (the WAL
            # reopens its own sink) NOR retry hot: count it and back off —
            # due() stays true, so the next write past the backoff retries
            self._compact_failures += 1
            backoff = min(2.0 ** self._compact_failures, 60.0)
            self._compact_backoff_until = time.monotonic() + backoff
            metrics.inc(COUNTER_COMPACT_FAILURES)
            logger.exception(
                "WAL compaction failed (failure %d in a row); next retry "
                "in %.0fs",
                self._compact_failures,
                backoff,
            )
        finally:
            self._compacting.clear()

    def backup_state(self) -> dict:
        """One-lock-consistent online backup image: the full object state
        at rv plus the consensus commit index and fencing term
        (runtime/backup.py writes it out; restore bumps the term so every
        pre-backup BindFence is structurally rejected)."""
        with self._lock:
            rv = self._rv
            objects = {
                kind: [serialization.encode(o) for o in store.values()]
                for kind, store in self._objects.items()
            }
        commit = rv
        term = 1
        rep = self.replicator
        if rep is not None:
            term = int(getattr(rep, "term", 1))
            cons = getattr(rep, "consensus", None)
            if cons is not None:
                commit = min(int(cons.commit_index), rv)
        return {
            "format": "ktpu-backup-v1",
            "rv": rv,
            "commit": commit,
            "term": term,
            "objects": objects,
        }

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _key(obj: Any) -> str:
        return obj.metadata.key

    @staticmethod
    def _normalize_scope(kind: str, obj: Any) -> None:
        """Cluster-scoped kinds store under namespace '' regardless of how
        the client spelled it (a plain manifest decode defaults to
        'default') — one canonical key, no per-consumer probe loops."""
        if kind in serialization.CLUSTER_SCOPED and obj.metadata.namespace:
            obj.metadata.namespace = ""

    @staticmethod
    def _normalize_ns(kind: str, namespace: str) -> str:
        if kind in serialization.CLUSTER_SCOPED:
            return ""
        return namespace

    def _bump(self, obj: Any) -> int:
        self._rv += 1
        obj.metadata.resource_version = self._rv
        return self._rv

    def _admit(self, verb: str, kind: str, obj: Any) -> None:
        for hook in self.admit_hooks:
            hook(verb, kind, obj)

    def _notify(self, kind: str, ev: Event) -> None:
        hist = self._history.setdefault(kind, deque(maxlen=self._history_len))
        if len(hist) == self._history_len and hist:
            # the append below evicts the oldest event: remember its rv so
            # watch() raises Expired exactly when a caller would actually
            # miss this kind's events (a global-rv heuristic would fire
            # spuriously for gaps made entirely of OTHER kinds' writes)
            self._evicted_rv[kind] = hist[0].resource_version
        hist.append(ev)
        for w in list(self._watchers.get(kind, [])):
            if w.stopped:
                self._watchers[kind].remove(w)
            else:
                w.push(ev)

    # -- CRUD ---------------------------------------------------------------

    @property
    def read_only(self) -> bool:
        return self.write_gate.fenced

    @read_only.setter
    def read_only(self, value: bool) -> None:
        self.write_gate.fenced = bool(value)

    def _check_writable(self) -> None:
        if self.write_gate.fenced:
            raise NotPrimary("store fenced: a newer primary holds the lease")
        # disk-pressure probe runs on the admission path so the store goes
        # read-only BEFORE appends fail and reopens when space recovers
        # (clients retrying a DiskPressure 503 drive the re-check)
        self._check_disk_pressure()
        # degraded read-only (consensus quorum lost / disk states): raises
        # the retryable DegradedWrites BEFORE any mutation is applied —
        # reads and watches are never gated
        self.write_gate.check_degraded()

    def create(self, kind: str, obj: Any) -> Any:
        self._check_writable()
        # admission runs OUTSIDE the store lock: webhook plugins do HTTP
        # round trips (and their handlers commonly read back from this
        # server), which under the lock would stall every API call and
        # deadlock read-back webhooks. In-process stateful gates serialize
        # themselves: QuotaAdmission check-and-reserves under its own mutex
        # (racing creates cannot both pass a quota with room for one,
        # matching the reference's transactional quota reservation)
        self._normalize_scope(kind, obj)
        self._admit("create", kind, obj)
        # always-on boundary validation AFTER admission mutators (the
        # reference's strategy.Validate ordering: defaulted fields are
        # validated, not raw input) — malformed objects 400 here instead
        # of surfacing later as encode-time scheduler exceptions
        validation.validate_object("create", kind, obj)
        with self._lock:
            store = self._objects.setdefault(kind, {})
            key = self._key(obj)
            if key in store:
                raise AlreadyExists(f"{kind} {key} already exists")
            if kind == "priorityclasses":
                # stateful uniqueness checks need the store lock (two
                # racing creates must not both land globalDefault: true)
                validation.validate_single_global_default(
                    obj, store.values()
                )
            self._bump(obj)
            stored = copy.deepcopy(obj)
            store[key] = stored
            self._log("create", kind, stored)
            self._notify(
                kind,
                Event(ADDED, copy.deepcopy(stored), stored.metadata.resource_version),
            )
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> Any:
        namespace = self._normalize_ns(kind, namespace)
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            store = self._objects.get(kind, {})
            if key not in store:
                raise NotFound(f"{kind} {key} not found")
            return copy.deepcopy(store[key])

    def update(self, kind: str, obj: Any, check_version: bool = True) -> Any:
        self._check_writable()
        self._normalize_scope(kind, obj)
        self._admit("update", kind, obj)  # outside the lock, see create()
        with self._lock:
            store = self._objects.setdefault(kind, {})
            key = self._key(obj)
            if key not in store:
                raise NotFound(f"{kind} {key} not found")
            cur = store[key]
            if (
                check_version
                and obj.metadata.resource_version
                and obj.metadata.resource_version != cur.metadata.resource_version
            ):
                raise Conflict(
                    f"{kind} {key}: rv {obj.metadata.resource_version} != "
                    f"{cur.metadata.resource_version}"
                )
            validation.validate_object("update", kind, obj, old=cur)
            if kind == "priorityclasses":
                validation.validate_single_global_default(
                    obj, (o for k, o in store.items() if k != key)
                )
            self._bump(obj)
            stored = copy.deepcopy(obj)
            # graceful deletion completes when the last finalizer is
            # stripped from a deletion-pending object (the registry's
            # deleteForEmptyFinalizers path)
            if (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            ):
                store.pop(key, None)
                self._log("delete", kind, stored)
                self._notify(
                    kind,
                    Event(
                        DELETED,
                        copy.deepcopy(stored),
                        stored.metadata.resource_version,
                    ),
                )
                return copy.deepcopy(stored)
            store[key] = stored
            self._log("update", kind, stored)
            self._notify(
                kind,
                Event(
                    MODIFIED, copy.deepcopy(stored), stored.metadata.resource_version
                ),
            )
            return copy.deepcopy(stored)

    def guaranteed_update(
        self, kind: str, namespace: str, name: str, mutate: Callable[[Any], Any]
    ) -> Any:
        """Retry-on-conflict read-modify-write (etcd3 GuaranteedUpdate)."""
        while True:
            cur = self.get(kind, namespace, name)
            new = mutate(cur)
            if new is None:
                return cur
            try:
                return self.update(kind, new)
            except Conflict:
                continue

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        self._check_writable()
        namespace = self._normalize_ns(kind, namespace)
        key = f"{namespace}/{name}" if namespace else name
        with self._lock:
            store = self._objects.get(kind, {})
            if key not in store:
                raise NotFound(f"{kind} {key} not found")
            admit_copy = copy.deepcopy(store[key])
        # outside the lock, see create(); validators get a copy so a
        # misbehaving plugin can't mutate stored state through the ref
        self._admit("delete", kind, admit_copy)
        with self._lock:
            store = self._objects.get(kind, {})
            if key not in store:
                raise NotFound(f"{kind} {key} not found")
            obj = store[key]
            if obj.metadata.finalizers:
                # graceful deletion (registry store.Delete with pending
                # finalizers): mark intent, keep the object; finalizer
                # owners strip their entries via update, and the LAST strip
                # removes it (see update())
                if obj.metadata.deletion_timestamp is None:
                    import time as _time

                    obj.metadata.deletion_timestamp = _time.time()
                    self._bump(obj)
                    self._log("update", kind, obj)
                    self._notify(
                        kind,
                        Event(
                            MODIFIED,
                            copy.deepcopy(obj),
                            obj.metadata.resource_version,
                        ),
                    )
                return copy.deepcopy(obj)
            store.pop(key)
            self._rv += 1
            self._log("delete", kind, obj)
            self._notify(kind, Event(DELETED, copy.deepcopy(obj), self._rv))
            return obj

    def list(
        self, kind: str, namespace: Optional[str] = None
    ) -> Tuple[List[Any], int]:
        """Returns (objects, resourceVersion-at-list-time)."""
        with self._lock:
            store = self._objects.get(kind, {})
            objs = [
                copy.deepcopy(o)
                for o in store.values()
                if namespace is None or o.metadata.namespace == namespace
            ]
            return objs, self._rv

    def pod_logs(
        self, namespace: str, name: str, tail_lines: Optional[int] = None
    ) -> str:
        """pods/{name}/log subresource: route to the pod's node's
        registered log provider (the kubelet-proxy hop of kubectl logs)."""
        pod = self.get("pods", namespace, name)
        node = pod.spec.node_name
        if not node:
            raise NotFound(f"pod {namespace}/{name} is not scheduled")
        provider = self.log_providers.get(node)
        if provider is None:
            raise NotFound(f"no log provider for node {node}")
        return provider(f"{namespace}/{name}", tail_lines)

    def pod_exec(self, namespace: str, name: str, command) -> str:
        """pods/{name}/exec subresource: ExecSync through the pod's node's
        registered exec provider (the kubelet hop of kubectl exec)."""
        pod = self.get("pods", namespace, name)
        node = pod.spec.node_name
        if not node:
            raise NotFound(f"pod {namespace}/{name} is not scheduled")
        provider = self.exec_providers.get(node)
        if provider is None:
            raise NotFound(f"no exec provider for node {node}")
        try:
            return provider(f"{namespace}/{name}", command)
        except KeyError as e:
            raise NotFound(str(e)) from None

    def exists(self, kind: str, key: str) -> bool:
        """O(1) copy-free presence check by store key ("ns/name")."""
        with self._lock:
            return key in self._objects.get(kind, {})

    def count(self, kind: str, predicate: Optional[Callable[[Any], bool]] = None) -> int:
        """Copy-free count over stored objects. The predicate runs under the
        store lock against live objects and MUST NOT mutate or retain them —
        it exists because a poll loop doing list() deep-copies the world per
        tick (observed: harness polling dominated a 5k-node benchmark)."""
        with self._lock:
            store = self._objects.get(kind, {})
            if predicate is None:
                return len(store)
            return sum(1 for o in store.values() if predicate(o))

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, from_version: int = 0) -> Watcher:
        """Watch a kind; events with rv > from_version are replayed first.

        Raises Expired ("resourceVersion too old", the reference's 410
        Gone from the etcd3 watcher / cacher) when the ring has already
        evicted events the caller would need: silently skipping them
        would hand the watcher a gapped stream it can't detect. Reflector
        equivalents respond by re-listing (SharedInformer does)."""
        with self._lock:
            hist = self._history.get(kind, ())
            evicted = self._evicted_rv.get(kind, 0)
            # from_version=0 is "from whenever" (no completeness contract);
            # list+watch pairs pass the list rv explicitly
            if from_version and from_version < evicted:
                raise Expired(
                    f"{kind} resourceVersion {from_version} is too old "
                    f"(events up to rv {evicted} were evicted)"
                )
            w = Watcher()
            for ev in hist:
                if ev.resource_version > from_version:
                    w.push(ev)
            self._watchers.setdefault(kind, []).append(w)
            return w

    def kind_resource_version(self, kind: str) -> int:
        """rv of the newest event OF THIS KIND (0 when none ever).
        The watch cache's freshness target: its per-kind rv can only
        ever reach this, not the global counter, which advances on
        every OTHER kind's writes too."""
        with self._lock:
            hist = self._history.get(kind)
            return hist[-1].resource_version if hist else 0

    def watcher_count(self, kind: str) -> int:
        """Live store-side watchers for a kind (stopped ones pruned).
        The watch cache's scale contract is asserted against this: N
        clients on the read path, exactly ONE watcher here per kind."""
        with self._lock:
            ws = [w for w in self._watchers.get(kind, []) if not w.stopped]
            self._watchers[kind] = ws
            return len(ws)

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    # -- typed convenience used by the scheduler ----------------------------

    def _check_fence(self, fence) -> None:
        """Caller holds the lock. Validates a leadership fencing token
        (client/leaderelection.BindFence, duck-typed: namespace/name/
        identity/transitions) against the CURRENT lease record. Any
        mismatch — taken over, released, or the lease gone entirely —
        raises LeaderFenced BEFORE anything is applied: the one-writer
        guarantee leader election promises is enforced here, not assumed."""
        ns = self._normalize_ns("leases", fence.namespace)
        key = f"{ns}/{fence.name}" if ns else fence.name
        lease = self._objects.get("leases", {}).get(key)
        if (
            lease is None
            or lease.holder_identity != fence.identity
            or lease.lease_transitions != fence.transitions
        ):
            holder = getattr(lease, "holder_identity", None)
            transitions = getattr(lease, "lease_transitions", None)
            raise LeaderFenced(
                f"bind fenced: lease {key} now held by {holder!r} at "
                f"transition {transitions} (caller's token: "
                f"{fence.identity!r} at {fence.transitions})"
            )

    def bind_pods(self, bindings, fence=None) -> list:
        """Batch bind: one lock acquisition for a whole device batch (the
        uplink analogue of the reference's per-pod POST /binding — our
        scheduler commits hundreds of placements per cycle, so the API layer
        accepts them in bulk). Returns per-binding errors (None = ok); an
        error entry is the NotFound/Conflict exception itself, so callers
        (the REST route's status mapping, the scheduler's reconciler)
        branch on type instead of re-deriving it from message text.

        fence: optional leadership fencing token (BindFence). When given,
        the WHOLE batch is rejected with LeaderFenced unless the token
        still matches the live lease — checked under the same lock the
        binds apply under, so a promotion can never interleave mid-batch.
        """
        from ..utils.tracing import stamp_bind

        self._check_writable()
        errors = []
        try:
            with self._lock:
                if fence is not None:
                    self._check_fence(fence)
                records = []  # WAL batch: group-committed in ONE fsync
                events = []
                for b in bindings:
                    try:
                        store = self._objects.get("pods", {})
                        key = f"{b.pod_namespace}/{b.pod_name}"
                        pod = store.get(key)
                        if pod is None:
                            raise NotFound(f"pods {key} not found")
                        if pod.spec.node_name:
                            raise Conflict(f"pod {key} already bound")
                        if b.pod_uid and pod.metadata.uid != b.pod_uid:
                            raise Conflict("uid mismatch on binding")
                        pod.spec.node_name = b.target_node
                        self._bump(pod)
                        records.append(
                            (pod.metadata.resource_version, "update", "pods", pod)
                        )
                        events.append(
                            Event(
                                MODIFIED,
                                event_copy(pod),
                                pod.metadata.resource_version,
                            )
                        )
                        errors.append(None)
                    except (NotFound, Conflict) as e:
                        errors.append(e)
                # durable BEFORE any watcher learns of the binds (etcd fires
                # watch events post-commit); the batch shares one fsync
                self._log_batch(records)
                for ev in events:
                    self._notify("pods", ev)
        except LeaderFenced as fe:
            # the fenced rejection is a trace event too: a zombie's late
            # bind shows up under the SAME id the deposed scheduler
            # minted (the id crossed the REST hop in X-Trace-Context)
            for b in bindings:
                stamp_bind(
                    b, "fenced",
                    identity=getattr(fence, "identity", ""),
                    detail=str(fe)[:160],
                )
            raise
        # store-side stamp: the ack the scheduler's trace resolves to
        # (outside the store lock — the trace ledger is a leaf concern)
        for b, err in zip(bindings, errors):
            stamp_bind(b, "applied" if err is None else type(err).__name__)
        return errors

    def write_events_bulk(self, events_in) -> None:
        """Event-recorder sink: upsert a drained batch of Event objects in
        ONE lock acquisition with ownership transfer — the recorder hands
        over freshly built objects and never touches them again, so the
        create path's three defensive deepcopies (~0.45 ms of GIL per
        event — per BOUND POD during a burst) are skipped. Watch delivery
        still isolates with a cheap shell copy; readers get deepcopies
        from get/list as usual. Existing (object, reason) rows aggregate
        count in place, matching the recorder's correlation semantics."""
        import dataclasses as _dc

        import dataclasses as _dc0

        self._check_writable()
        # admit/validate with the verb the apply below will actually use
        # (aggregating onto an existing row is an update, not a create) so
        # verb-sensitive hooks see the same stream as the per-event path.
        # Existence is snapshotted briefly under the lock; a concurrent
        # recorder racing the same key can at worst mis-verb one
        # best-effort event write.
        with self._lock:
            ev_store = self._objects.get("events", {})
            olds = {}
            for ev in events_in:
                self._normalize_scope("events", ev)
                cur = ev_store.get(self._key(ev))
                if cur is not None:
                    olds[id(ev)] = _dc0.replace(
                        cur, metadata=_dc0.replace(cur.metadata)
                    )
        for ev in events_in:
            old = olds.get(id(ev))
            verb = "create" if old is None else "update"
            self._admit(verb, "events", ev)
            validation.validate_object(verb, "events", ev, old=old)
        with self._lock:
            store = self._objects.setdefault("events", {})
            records = []
            notifies = []
            for ev in events_in:
                key = self._key(ev)
                cur = store.get(key)
                if cur is not None:
                    cur.count += ev.count
                    cur.last_timestamp = ev.last_timestamp
                    cur.note = ev.note
                    self._bump(cur)
                    records.append(
                        (cur.metadata.resource_version, "update", "events", cur)
                    )
                    notifies.append(
                        Event(
                            MODIFIED,
                            _dc.replace(
                                cur, metadata=_dc.replace(cur.metadata)
                            ),
                            cur.metadata.resource_version,
                        )
                    )
                else:
                    self._bump(ev)
                    store[key] = ev
                    records.append(
                        (ev.metadata.resource_version, "create", "events", ev)
                    )
                    notifies.append(
                        Event(
                            ADDED,
                            _dc.replace(
                                ev, metadata=_dc.replace(ev.metadata)
                            ),
                            ev.metadata.resource_version,
                        )
                    )
            self._log_batch(records)
            for e in notifies:
                self._notify("events", e)

    def evict_pod(self, namespace: str, name: str) -> None:
        """pods/{name}/eviction: a PDB-respecting delete (reference
        registry/core/pod/rest/eviction.go). Blocked evictions raise
        TooManyRequests (HTTP 429) and consume no budget; allowed ones
        decrement every covering PDB's disruptionsAllowed optimistically,
        exactly like the registry's checkAndDecrement."""
        self._check_writable()
        with self._lock:
            pods = self._objects.get("pods", {})
            key = f"{namespace}/{name}"
            pod = pods.get(key)
            if pod is None:
                raise NotFound(f"pods {key} not found")
            if (
                pod.status.phase in ("Succeeded", "Failed")
                or pod.metadata.deletion_timestamp is not None
            ):
                # terminal or already-terminating pods disrupt nothing: no
                # PDB check, no budget charge (eviction.go deletes them
                # outright; a drain retry must not double-charge)
                covering = []
            else:
                covering = self._covering_pdbs(namespace, pod)
            for pdb in covering:
                if pdb.status.disruptions_allowed <= 0:
                    raise TooManyRequests(
                        f"Cannot evict pod as it would violate the pod's "
                        f"disruption budget {pdb.metadata.name}"
                    )
            for pdb in covering:
                pdb.status.disruptions_allowed -= 1
                self._bump(pdb)
                self._log("update", "poddisruptionbudgets", pdb)
                self._notify(
                    "poddisruptionbudgets",
                    Event(
                        MODIFIED,
                        copy.deepcopy(pdb),
                        pdb.metadata.resource_version,
                    ),
                )
        self.delete("pods", namespace, name)

    def _covering_pdbs(self, namespace: str, pod) -> list:
        from ..api.selectors import match_labels

        # NOTE no truthiness guard on the selector: the empty selector
        # matches everything (selectors.match_labels convention) — the
        # disruption controller and preemptor treat it that way, and the
        # eviction gate must agree with them
        return [
            pdb
            for pdb in self._objects.get("poddisruptionbudgets", {}).values()
            if pdb.metadata.namespace == namespace
            and match_labels(pdb.spec.selector, pod.metadata.labels)
        ]

    def bind_pod(self, binding) -> None:
        """POST pods/{name}/binding: set spec.nodeName if not already bound.

        Reference: registry/core/pod/storage BindingREST -> assignPod; the
        scheduler calls it via DefaultBinder
        (framework/plugins/defaultbinder/default_binder.go:50).
        """

        def mutate(pod):
            if pod.spec.node_name:
                raise Conflict(
                    f"pod {binding.pod_namespace}/{binding.pod_name} already bound"
                )
            if binding.pod_uid and pod.metadata.uid != binding.pod_uid:
                raise Conflict("uid mismatch on binding")
            pod.spec.node_name = binding.target_node
            return pod

        self.guaranteed_update("pods", binding.pod_namespace, binding.pod_name, mutate)


# lockset sanitizer (testing/lockgraph.py Eraser mode): the store's
# object/watcher/history maps are guarded by the `store` lock on every
# CRUD, notify, and replication-catchup path. `_rv` is deliberately NOT
# tracked: the replication heartbeat piggybacks a lock-free int peek of
# it by design (runtime/replication.py _heartbeat_loop).
track_attrs(APIServer, "_objects", "_watchers", "_history", "_evicted_rv")
